"""Lifecycle/topology tests.

Mirrors the reference's rank/size ground-truth checks (reference:
test/test_tensorflow.py:92-107 test_horovod_rank/test_horovod_size).
"""

import pytest


def test_init_size_rank(hvd):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.local_size() == 4
    assert hvd.cross_size() == 2
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.cross_rank() == 0


def test_double_init_is_noop(hvd):
    mesh_before = hvd.mesh()
    hvd.init(mesh_shape=(1, 8))  # ignored: already initialized
    assert hvd.mesh() is mesh_before
    assert hvd.size() == 8


def test_not_initialized_raises():
    import horovod_tpu as hvd

    hvd.shutdown()
    with pytest.raises(RuntimeError, match="init"):
        hvd.rank()
    with pytest.raises(RuntimeError, match="init"):
        hvd.size()


def test_shutdown_and_reinit(hvd):
    hvd.shutdown()
    assert not hvd.is_initialized()
    hvd.init(mesh_shape=(1, 8))
    assert hvd.size() == 8
    assert hvd.local_size() == 8


def test_mesh_axes(hvd):
    assert hvd.mesh().axis_names == (hvd.CROSS_AXIS, hvd.LOCAL_AXIS)
    assert hvd.mesh().devices.shape == (2, 4)


def test_mesh_shape_env(monkeypatch):
    import horovod_tpu as hvd

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_MESH_SHAPE", "4,2")
    hvd.init()
    assert hvd.cross_size() == 4
    assert hvd.local_size() == 2
    hvd.shutdown()


def test_bad_mesh_shape(hvd):
    hvd.shutdown()
    with pytest.raises(ValueError, match="does not cover"):
        hvd.init(mesh_shape=(3, 2))


def test_is_homogeneous(hvd):
    assert hvd.is_homogeneous()


def test_built_probes(hvd):
    # reference: horovod_mpi_built etc. (operations.cc:640-732); the TPU
    # build's transports are XLA, not MPI/NCCL/Gloo.
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.gloo_built()
    assert not hvd.nccl_built()
    assert not hvd.mpi_enabled()


def test_config_from_env(monkeypatch):
    import horovod_tpu as hvd
    from horovod_tpu.core import state

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1048576")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "16")
    hvd.init(mesh_shape=(1, 8))
    cfg = state.global_state().config
    assert cfg.fusion_threshold_bytes == 1048576
    assert cfg.cycle_time_ms == 2.5
    assert cfg.cache_capacity == 16
    hvd.shutdown()
