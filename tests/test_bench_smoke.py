"""bench.py smoke coverage (tier-1 safe).

The benchmark harness is driver-facing: a module-level typo or a stale
API call would otherwise only surface in a perf run. Import it and run
the two microbench suites in --tiny mode — every code path (runtime
enqueue, program-cache warmup checks, flight-recorder A/B, the ZeRO-1
replicated-vs-sharded comparison and its JSON schema) in seconds.
"""

import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench(hvd):
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench as bench_mod

    return bench_mod


def test_bench_imports_and_flags(bench):
    # the sweep's workload table stays importable and complete
    assert callable(bench.collectives_main)
    assert callable(bench.sharded_optimizer_main)
    assert callable(bench.control_plane_main)
    assert "resnet50" in bench.CNN_CONFIGS


def test_collectives_suite_tiny(bench, capsys):
    result = bench.collectives_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "ms"
    assert result["sizes"], "no size rows emitted"
    # the emitted line is valid single-line JSON (driver contract)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["metric"] == result["metric"]


def test_sharded_optimizer_tiny(bench, capsys):
    result = bench.sharded_optimizer_main(tiny=True)
    assert result["tiny"] is True
    b = result["opt_state_bytes_per_chip"]
    assert 0 < b["sharded"] < b["replicated"]
    # sharded state must actually shrink toward 1/N (padding-limited on
    # toy shapes, so just require a real reduction)
    assert result["state_bytes_reduction_x"] > 1.5
    assert result["steady_state_program_builds"] == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]
