"""bench.py smoke coverage (tier-1 safe).

The benchmark harness is driver-facing: a module-level typo or a stale
API call would otherwise only surface in a perf run. Import it and run
the two microbench suites in --tiny mode — every code path (runtime
enqueue, program-cache warmup checks, flight-recorder A/B, the ZeRO-1
replicated-vs-sharded comparison and its JSON schema) in seconds.
"""

import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def bench(hvd):
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench as bench_mod

    return bench_mod


def test_bench_imports_and_flags(bench):
    # the sweep's workload table stays importable and complete
    assert callable(bench.collectives_main)
    assert callable(bench.sharded_optimizer_main)
    assert callable(bench.control_plane_main)
    assert "resnet50" in bench.CNN_CONFIGS


def test_collectives_suite_tiny(bench, capsys):
    result = bench.collectives_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "ms"
    assert result["sizes"], "no size rows emitted"
    # the emitted line is valid single-line JSON (driver contract)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["metric"] == result["metric"]


def test_integrity_suite_tiny(bench, capsys, monkeypatch):
    """PR 10 acceptance shape: the --integrity microbench emits one JSON
    line with the off/default/every-dispatch p50s and the zero-compile
    canary; the env knobs it toggles are restored afterwards."""
    monkeypatch.delenv("HOROVOD_INTEGRITY", raising=False)
    result = bench.integrity_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "%"
    assert result["goal"] == "< 1%"
    assert result["p50_ms_integrity_off"] > 0
    assert result["p50_ms_default_interval"] > 0
    assert result["p50_ms_every_dispatch"] > 0
    # warmup compiled the digest program; the timed phases reuse it
    assert result["steady_state_compiles"] == 0
    assert result["digest_checks_timed_phase"] >= 1
    assert os.environ.get("HOROVOD_INTEGRITY") is None
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


def test_sharded_optimizer_tiny(bench, capsys):
    result = bench.sharded_optimizer_main(tiny=True)
    assert result["tiny"] is True
    b = result["opt_state_bytes_per_chip"]
    assert 0 < b["sharded"] < b["replicated"]
    # sharded state must actually shrink toward 1/N (padding-limited on
    # toy shapes, so just require a real reduction)
    assert result["state_bytes_reduction_x"] > 1.5
    assert result["steady_state_program_builds"] == 0
    # per-stage rows (ZeRO 1/2/3): stage 2 halves the gradient wire
    # bytes (RS only, no grad AG); stage 3 additionally shards params at
    # rest; every stage keeps the zero-steady-state-compile invariant
    stages = result["stages"]
    assert set(stages) == {"stage1", "stage2", "stage3"}
    s1, s2, s3 = stages["stage1"], stages["stage2"], stages["stage3"]
    for row in (s1, s2, s3):
        assert row["steady_state_builds"] == 0
        assert set(row["bytes_per_chip"]) == {
            "params", "grads", "optimizer_state"}
    assert s2["grad_wire_bytes_per_step"] * 2 == s1[
        "grad_wire_bytes_per_step"]
    assert s3["grad_wire_bytes_per_step"] == s2["grad_wire_bytes_per_step"]
    assert s2["bytes_per_chip"]["grads"] < s1["bytes_per_chip"]["grads"]
    assert s3["bytes_per_chip"]["params"] < s1["bytes_per_chip"]["params"]
    assert 0.0 <= s3["gather_hidden_fraction"] <= 1.0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


def test_tiny_flagship_emits_step_breakdown(bench, capsys, monkeypatch):
    """PR 6 acceptance: bare ``python bench.py --tiny`` — here its entry
    function — emits a headline carrying step_breakdown +
    comm_hidden_fraction from the step profiler."""
    result = bench.tiny_main()
    # tiny_main enables the step profiler via os.environ + configure();
    # undo BOTH the env var and the module state, or every later test in
    # the session sees profiler.enabled() == True
    monkeypatch.delenv("HOROVOD_PROFILE", raising=False)
    from horovod_tpu import profiler
    profiler.configure()
    assert result["tiny"] is True
    phases = result["step_breakdown"]
    assert set(phases) == {"host", "compute", "exposed_comm", "optimizer"}
    assert sum(phases.values()) > 0
    assert 0.0 <= result["comm_hidden_fraction"] <= 1.0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["step_breakdown"] == phases


# ---------------------------------------------------------------------------
# bench_compare regression gate
# ---------------------------------------------------------------------------

_REPO_TOOLS = os.path.join(_REPO, "tools")


@pytest.fixture
def bench_compare():
    if _REPO_TOOLS not in sys.path:
        sys.path.insert(0, _REPO_TOOLS)
    import bench_compare as mod

    return mod


def _artifact(path, rows):
    tail = "\n".join(["benchmark log noise"]
                     + [json.dumps(r) for r in rows])
    with open(path, "w") as f:
        json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                   "tail": tail}, f)
    return str(path)


_BASE_ROW = {"metric": "images/sec/chip (ResNet-50 synthetic)",
             "value": 2000.0, "unit": "images/sec/chip", "mfu": 0.5,
             "step_breakdown": {"host": 0.002, "compute": 0.04,
                                "exposed_comm": 0.003, "optimizer": 0.005}}


def test_bench_compare_clean_pass(bench_compare, tmp_path, capsys):
    base = _artifact(tmp_path / "base.json", [_BASE_ROW])
    cand_row = dict(_BASE_ROW, value=1980.0)  # -1%: inside the gate
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 0
    out = capsys.readouterr().out
    assert "no regressions" in out


def test_bench_compare_degraded_candidate_fails(bench_compare, tmp_path,
                                                capsys):
    base = _artifact(tmp_path / "base.json", [_BASE_ROW])
    cand_row = dict(_BASE_ROW, value=1500.0)  # -25% throughput
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    rc = bench_compare.main(["--baseline", base, "--candidate", cand,
                             "--threshold-pct", "5"])
    assert rc == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_bench_compare_phase_regression_fails(bench_compare, tmp_path,
                                              capsys):
    # throughput flat but exposed comm tripled: the phase row catches it
    base = _artifact(tmp_path / "base.json", [_BASE_ROW])
    cand_row = dict(_BASE_ROW)
    cand_row["step_breakdown"] = dict(_BASE_ROW["step_breakdown"],
                                      exposed_comm=0.009)
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    assert "exposed_comm seconds" in capsys.readouterr().out


def test_bench_compare_expands_summary_and_skips_tiny(bench_compare,
                                                      tmp_path):
    # truncated-run shape: the only row is a cumulative summary line
    summary = {"metric": "summary — all headlines", "value": 1.0,
               "unit": "tokens/sec/chip",
               "results": [_BASE_ROW,
                           {"metric": "tiny row", "value": 5.0,
                            "unit": "ms", "tiny": True}]}
    rows = bench_compare.derived_rows(
        bench_compare.parse_artifact(
            _artifact(tmp_path / "sum.json", [summary])))
    assert "images/sec/chip (ResNet-50 synthetic)" in rows
    assert not any("tiny" in k for k in rows)
    assert not any(k.startswith("summary") for k in rows)


def test_bench_compare_real_artifacts(bench_compare):
    """The repo's own trajectory must pass its own gate (PR 6
    acceptance: r04 -> r05 runs clean)."""
    r04 = os.path.join(_REPO, "BENCH_r04.json")
    r05 = os.path.join(_REPO, "BENCH_r05.json")
    if not (os.path.exists(r04) and os.path.exists(r05)):
        pytest.skip("BENCH artifacts not present")
    assert bench_compare.main([r04, r05]) == 0


def test_bench_compare_r05_to_r06(bench_compare):
    """ISSUE 12 acceptance: the bucket-wise gradient release round must
    clear the gate against r05 — ResNet-50 and Inception-V3 MFU up well
    past the 5% threshold, nothing else regressed."""
    r05 = os.path.join(_REPO, "BENCH_r05.json")
    r06 = os.path.join(_REPO, "BENCH_r06.json")
    if not (os.path.exists(r05) and os.path.exists(r06)):
        pytest.skip("BENCH artifacts not present")
    assert bench_compare.main([r05, r06]) == 0


def test_bench_compare_memory_row_regression_fails(bench_compare,
                                                   tmp_path, capsys):
    """ISSUE 13 acceptance: memory rows are direction-aware. Throughput
    flat but the grads footprint doubled — the bytes sub-metric (lower
    is better) fails the gate on its own."""
    base_row = dict(_BASE_ROW,
                    bytes_per_chip={"params": 4.0e8, "grads": 4.0e8},
                    peak_hbm_bytes=1.2e9)
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(base_row,
                    bytes_per_chip={"params": 4.0e8, "grads": 8.0e8})
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "grads bytes" in out
    assert "lower is better" in out


def test_bench_compare_memory_rows_clean_pass(bench_compare, tmp_path,
                                              capsys):
    # identical footprints (and a peak watermark) compare clean
    row = dict(_BASE_ROW, bytes_per_chip={"params": 4.0e8},
               peak_hbm_bytes=1.2e9)
    base = _artifact(tmp_path / "base.json", [row])
    cand = _artifact(tmp_path / "cand.json", [dict(row)])
    assert bench_compare.main([base, cand]) == 0
    out = capsys.readouterr().out
    assert "params bytes" in out
    assert "peak_hbm bytes" in out


def test_bench_compare_serve_p99_regression_fails(bench_compare,
                                                  tmp_path, capsys):
    """ISSUE 15 satellite: serving tail latencies are direction-aware
    sub-metrics. Throughput flat but the candidate's p99 latency
    tripled — the ms row (lower is better) fails the gate on its own."""
    serve_row = {"metric": "tokens/sec/chip (serving, continuous "
                           "batching)",
                 "value": 5000.0, "unit": "tokens/sec/chip",
                 "p50_latency_ms": 80.0, "p99_latency_ms": 200.0,
                 "p50_ttft_ms": 20.0, "p99_ttft_ms": 60.0}
    base = _artifact(tmp_path / "base.json", [serve_row])
    cand = _artifact(tmp_path / "cand.json",
                     [dict(serve_row, p99_latency_ms=600.0)])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "p99_latency_ms" in out
    assert "lower is better" in out
    # p50 + TTFT rows held steady and compare clean
    assert "        ok  tokens/sec/chip (serving, continuous batching) " \
           "[p50_latency_ms]" in out


def test_bench_compare_serve_rows_clean_pass(bench_compare, tmp_path,
                                             capsys):
    row = {"metric": "tokens/sec/chip (serving)", "value": 5000.0,
           "unit": "tokens/sec/chip", "p50_latency_ms": 80.0,
           "p99_latency_ms": 200.0, "p50_ttft_ms": 20.0,
           "p99_ttft_ms": 60.0}
    base = _artifact(tmp_path / "base.json", [row])
    cand = _artifact(tmp_path / "cand.json", [dict(row)])
    assert bench_compare.main([base, cand]) == 0
    out = capsys.readouterr().out
    for key in ("p50_latency_ms", "p99_latency_ms", "p50_ttft_ms",
                "p99_ttft_ms"):
        assert key in out


def test_bench_compare_usage_errors(bench_compare, tmp_path):
    assert bench_compare.main([]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("not json")
    good = _artifact(tmp_path / "good.json", [_BASE_ROW])
    assert bench_compare.main([str(bad), good]) == 2


def test_serve_suite_tiny(bench, capsys):
    """PR 11 acceptance shape: ``bench.py --serve --tiny`` sustains
    Poisson traffic on 2 in-process replicas with batch occupancy > 1,
    compiles NOTHING after the per-bucket warmup, and reports the
    serving headline as one JSON line."""
    result = bench.serve_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "tokens/sec/chip"
    assert result["value"] > 0
    assert result["replicas"] == 2
    assert result["requests"] == 16
    assert result["avg_batch_occupancy"] > 1.0
    assert result["steady_state_compiles"] == 0
    assert result["warmup_compiles"] > 0
    assert result["p99_latency_ms"] >= result["p50_latency_ms"] > 0
    assert result["p99_ttft_ms"] >= result["p50_ttft_ms"] > 0
    # ISSUE 13 satellite: KV-cache footprint rides the serving headline
    assert result["kv_cache_bytes_per_chip"] > 0
    assert 0.0 <= result["kv_utilization"] <= 1.0
    # ISSUE 15: the interleaved tracing A/B rode along (goal < 1% on
    # decode p50 — asserted loosely here, --tiny numbers are noisy) and
    # the SLO plane scored every request in the run
    assert isinstance(result["tracing_overhead_pct"], float)
    assert result["spans_recorded"] > 0
    assert result["slo_requests_scored"] >= result["requests"]
    assert set(result["slo_burn_rate"]) == \
        {"ttft", "latency", "availability"}
    for obj, budget in result["slo_error_budget_remaining"].items():
        assert 0.0 <= budget <= 1.0, obj
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


def test_memory_suite_tiny(bench, capsys):
    """ISSUE 13 acceptance shape: ``bench.py --memory --tiny`` runs the
    interleaved tracker-off/tracker-on A/B and reports the overhead
    headline plus the per-subsystem footprint as one JSON line."""
    result = bench.memory_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "%"
    assert result["goal"] == "< 1%"
    assert result["p50_ms_memory_off"] > 0
    assert result["p50_ms_memory_on"] > 0
    assert result["samples_taken"] >= 1
    per_chip = result["bytes_per_chip"]
    assert per_chip and per_chip.get("grads", 0) > 0
    assert result["peak_hbm_bytes"] > 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


def test_bench_compare_deflated_busbw_fails(bench_compare, tmp_path,
                                            capsys):
    """ISSUE 16 satellite: comms rows are higher-is-better sub-metrics.
    Throughput flat but the candidate's bus bandwidth halved — the GB/s
    row fails the gate on its own."""
    base_row = dict(_BASE_ROW, busbw_gbs=40.0, comms_utilization=0.8)
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(base_row, busbw_gbs=20.0, comms_utilization=0.4)
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "busbw_gbs" in out
    assert "comms_utilization" in out
    assert "higher is better" in out


def test_bench_compare_comms_rows_clean_pass(bench_compare, tmp_path,
                                             capsys):
    row = dict(_BASE_ROW, busbw_gbs=40.0, comms_utilization=0.8)
    base = _artifact(tmp_path / "base.json", [row])
    cand = _artifact(tmp_path / "cand.json", [dict(row)])
    assert bench_compare.main([base, cand]) == 0
    out = capsys.readouterr().out
    assert "[busbw_gbs]" in out
    assert "[comms_utilization]" in out


def test_bench_compare_inflated_kv_bytes_fails(bench_compare, tmp_path,
                                               capsys):
    """ISSUE 17 satellite: kv_cache_bytes_per_chip is a lower-is-better
    bytes row. Throughput flat but the candidate's KV footprint doubled
    (paged engine regressed to dense-sized pools) — the bytes row fails
    the gate on its own."""
    base_row = dict(_BASE_ROW, kv_cache_bytes_per_chip=98304.0)
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(base_row, kv_cache_bytes_per_chip=196608.0)
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "kv_cache bytes" in out
    assert "lower is better" in out


def test_bench_compare_collapsed_prefix_hit_rate_fails(bench_compare,
                                                       tmp_path, capsys):
    """ISSUE 17 satellite: prefix_hit_rate is a higher-is-better
    fraction — a collapsed hit rate (prefix cache silently disabled)
    gates like a throughput regression even when latency holds."""
    base_row = dict(_BASE_ROW, prefix_hit_rate=0.8)
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(base_row, prefix_hit_rate=0.1)
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "prefix_hit_rate" in out
    assert "higher is better" in out


def test_bench_compare_paged_rows_clean_pass(bench_compare, tmp_path,
                                             capsys):
    row = dict(_BASE_ROW, kv_cache_bytes_per_chip=98304.0,
               prefix_hit_rate=0.8)
    base = _artifact(tmp_path / "base.json", [row])
    cand = _artifact(tmp_path / "cand.json", [dict(row)])
    assert bench_compare.main([base, cand]) == 0
    out = capsys.readouterr().out
    assert "[kv_cache bytes]" in out
    assert "[prefix_hit_rate]" in out


def test_comms_suite_tiny(bench, capsys):
    """ISSUE 16 satellite shape: ``bench.py --comms --tiny`` runs the
    interleaved tracker-off/tracker-on A/B and reports the overhead
    headline as one JSON line with zero steady-state compiles."""
    result = bench.comms_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "%"
    assert result["goal"] == "< 1%"
    assert result["p50_ms_comms_off"] > 0
    assert result["p50_ms_comms_on"] > 0
    assert result["steady_state_compiles"] == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


_STAGE_ROW = {
    "update_p50_ms": 3.0,
    "bytes_per_chip": {"params": 4096, "grads": 4096,
                       "optimizer_state": 12288},
    "grad_wire_bytes_per_step": 8192,
    "wire_bytes_per_step": 8192,
    "steady_state_builds": 0,
}


def test_bench_compare_stage_wire_regression_fails(bench_compare,
                                                   tmp_path, capsys):
    """ISSUE 20 satellite: per-stage ZeRO rows gate direction-aware. The
    headline holds but stage 2's gradient wire bytes double back to the
    allreduce cost (the reduce-scatter release silently fell back) — the
    bytes row fails the gate on its own."""
    base_row = dict(_BASE_ROW, stages={
        "stage1": dict(_STAGE_ROW),
        "stage2": dict(_STAGE_ROW, grad_wire_bytes_per_step=4096,
                       bytes_per_chip={"params": 4096, "grads": 512,
                                       "optimizer_state": 12288}),
    })
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(_BASE_ROW, stages={
        "stage1": dict(_STAGE_ROW),
        "stage2": dict(_STAGE_ROW, grad_wire_bytes_per_step=8192,
                       bytes_per_chip={"params": 4096, "grads": 512,
                                       "optimizer_state": 12288}),
    })
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "[stage2 grad_wire_bytes_per_step]" in out
    assert "lower is better" in out


def test_bench_compare_stage_rows_gate_builds_and_hidden(bench_compare,
                                                         tmp_path,
                                                         capsys):
    """Steady-state builds regressing 0 -> N and a collapsed stage-3
    comm-hidden fraction both fail; identical artifacts pass with the
    stage rows compared."""
    base_row = dict(_BASE_ROW, stages={
        "stage3": dict(_STAGE_ROW, steady_state_builds=2,
                       gather_hidden_fraction=0.6)})
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(_BASE_ROW, stages={
        "stage3": dict(_STAGE_ROW, steady_state_builds=4,
                       gather_hidden_fraction=0.1)})
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "[stage3 steady_state_builds]" in out
    assert "[stage3 gather_hidden_fraction]" in out

    same = _artifact(tmp_path / "same.json", [base_row])
    assert bench_compare.main([base, same]) == 0
    out = capsys.readouterr().out
    assert "[stage3 update_p50_ms]" in out
    assert "[stage3 params bytes]" in out
