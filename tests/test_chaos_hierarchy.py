"""Fast-tier repeat of the hierarchy chaos cell (ISSUE 18 satellite).

A four-rank hierarchical world (2 groups of 2, cross hop throttled with
``netdelay:hop=cross``) loses rank 3 at step 2: the survivors re-form
at world 3, where 3 % 2 != 0 — the executor must recompute the plan for
the new world (flat fallback, not a wedge on the stale 2x2 grouping
keyed to the dead transport) and finish with zero lost steps. The
richer cell — kills landing a six-rank world on a REGROUPABLE world 4
where hierarchy re-enables — runs in tools/chaos_matrix.py
(``hier_cross_kill``); this is the tier-1 smoke of the same seam.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.runtime.native import native_built

pytestmark = [
    pytest.mark.skipif(not native_built(),
                       reason="native transport not built"),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "chaos_worker.py")
TOTAL = 5


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_rank_killed_mid_cross_exchange_reforms_and_finishes(tmp_path):
    world = 4
    server = RendezvousServer(host="127.0.0.1")
    http_port = server.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_MIN_WORKERS": "3",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                "HOROVOD_HIERARCHY_GROUP_SIZE": "2",
                # the throttled cross hop widens the exchange window so
                # the kill lands while survivors are inside it
                "HOROVOD_FAULT_INJECT":
                    "netdelay:3:hop=cross;kill:rank=3:step=2:code=17",
                "HOROVOD_FLIGHT_RECORDER_DIR": str(tmp_path),
                "CHAOS_TOTAL_STEPS": str(TOTAL),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        results = {}
        for rank, proc in enumerate(procs):
            out, _ = proc.communicate(timeout=180)
            want = 17 if rank == 3 else 0
            assert proc.returncode == want, \
                f"rank {rank} exited {proc.returncode}:\n{out[-2000:]}"
            for line in out.splitlines():
                if line.startswith("CHAOS_RESULT "):
                    results[rank] = json.loads(
                        line[len("CHAOS_RESULT "):])
        assert sorted(results) == [0, 1, 2]  # rank 3 died before report
        for rank, res in results.items():
            # zero lost steps across the re-form
            assert res["step"] == TOTAL, res
            assert abs(res["w"] - TOTAL) <= 1e-4, res
            assert res["generation"] >= 1, res
            # world 3 cannot split into groups of 2: the recomputed plan
            # fell back flat instead of wedging on the stale grouping
            assert res["hier_enabled"] is False, res
        # the throttled cross hop actually fired before the re-form
        assert sum(r["chaos_injected_total"]
                   for r in results.values()) > 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
