"""Multiprocess network-chaos acceptance test (one fast scenario).

Runs the ``flaky_negotiate`` cell of the chaos matrix inline under
pytest: two real worker processes train over the socket controller
while every control-plane transport op fails with probability 0.3 for
the first seconds of the run. Training must complete with zero lost
steps (``w == step == TOTAL``) and a nonzero
``horovod_net_retries_total`` — proving the retry layer, not luck,
bridged the faults. The full fault-mode × phase matrix (kv outage
during re-form, permanent partition + collective timeout + postmortem
attribution, netdelay) lives in tools/chaos_matrix.py.

Marked slow: tier-1 already runs within a few percent of its wall-clock
budget, and the in-process halves of this coverage (retry/backoff,
kv_outage bridging, chaos grammar) are tier-1 via tests/test_resilience.py.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.runtime.native import native_built

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not native_built(),
                       reason="native transport not built"),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "chaos_worker.py")
TOTAL = 6


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_flaky_negotiate_completes_with_retries(tmp_path):
    world = 2
    server = RendezvousServer(host="127.0.0.1")
    http_port = server.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_MIN_WORKERS": str(world),
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "HOROVOD_FAULT_INJECT": "flaky:0.3:seconds=4",
                "HOROVOD_NET_MAX_RETRIES": "12",
                "HOROVOD_FLIGHT_RECORDER_DIR": str(tmp_path),
                "CHAOS_TOTAL_STEPS": str(TOTAL),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        results = {}
        for rank, proc in enumerate(procs):
            out, _ = proc.communicate(timeout=120)
            assert proc.returncode == 0, \
                f"rank {rank} exited {proc.returncode}:\n{out[-2000:]}"
            for line in out.splitlines():
                if line.startswith("CHAOS_RESULT "):
                    results[rank] = json.loads(
                        line[len("CHAOS_RESULT "):])
        assert sorted(results) == list(range(world))
        for rank, res in results.items():
            assert res["step"] == TOTAL, res
            assert abs(res["w"] - TOTAL) <= 1e-4, res
        # the faults were real and the retry layer absorbed them
        assert sum(r["net_retries_total"] for r in results.values()) > 0
        assert sum(r["net_gave_up_total"] for r in results.values()) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
