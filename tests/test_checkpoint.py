"""Checkpoint/resume: rank-0 save + broadcast restore round trips."""

import os

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import checkpoint, training
from horovod_tpu.models.mnist import MnistConvNet


class TestCheckpoint:
    def _state(self, hvd):
        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        return model, opt, training.create_train_state(
            model, opt, (1, 28, 28, 1))

    def test_save_restore_roundtrip(self, hvd, tmp_path):
        _, _, state = self._state(hvd)
        d = str(tmp_path / "ckpts")
        path = checkpoint.save(d, {"params": state.params}, step=3)
        assert path and os.path.exists(path)

        # restore into the true structure
        target = {"params": state.params}
        restored = checkpoint.restore(path, target)
        flat_a = np.concatenate([np.asarray(x).ravel() for x in
                                 _leaves(restored)])
        flat_b = np.concatenate([np.asarray(x).ravel() for x in
                                 _leaves(target)])
        np.testing.assert_allclose(flat_a, flat_b)

    def test_restore_latest_and_keep(self, hvd, tmp_path):
        d = str(tmp_path / "ckpts")
        tree = {"w": jnp.arange(4.0)}
        for s in (1, 5, 9):
            checkpoint.save(d, {"w": tree["w"] * s}, step=s, keep=2)
        assert checkpoint.all_steps(d) == [5, 9]

        restored, step = checkpoint.restore_latest(d, tree)
        assert step == 9
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(4.0) * 9)

    def test_restore_latest_empty_dir(self, hvd, tmp_path):
        tree = {"w": jnp.arange(4.0)}
        restored, step = checkpoint.restore_latest(
            str(tmp_path / "nope"), tree)
        assert step is None
        assert restored is tree

    def test_full_train_resume(self, hvd, tmp_path):
        """Train, checkpoint, perturb, resume — resumed state matches."""
        import jax

        model, opt, state = self._state(hvd)
        step_fn, sh = training.make_train_step(model, opt, donate=False)
        rng = np.random.RandomState(0)
        images = jax.device_put(rng.rand(16, 28, 28, 1).astype(np.float32), sh)
        labels = jax.device_put(rng.randint(0, 10, (16,)).astype(np.int32), sh)

        loss, params, stats, opt_state = step_fn(
            state.params, state.batch_stats, state.opt_state, images, labels)
        d = str(tmp_path / "ckpts")
        tree = {"params": params, "batch_stats": stats,
                "opt_state": opt_state}
        checkpoint.save(d, tree, step=1)

        restored, step = checkpoint.restore_latest(d, tree)
        assert step == 1
        # one more step from the restored state reproduces the original
        l1, p1, _, _ = step_fn(restored["params"], restored["batch_stats"],
                               restored["opt_state"], images, labels)
        l2, p2, _, _ = step_fn(params, stats, opt_state, images, labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


class TestCheckpointIntegrity:
    """The ``.crc`` sidecar must turn silent disk damage into a typed,
    leaf-naming :class:`CheckpointCorruptError` (PR-9 regression: a
    truncated msgpack used to parse into garbage silently)."""

    def _save(self, tmp_path):
        d = str(tmp_path / "ckpts")
        tree = {"params": {"w": jnp.arange(64, dtype=jnp.float32),
                           "b": jnp.ones((8,), jnp.float32)}}
        path = checkpoint.save(d, tree, step=1)
        assert os.path.exists(path + ".crc")
        return path, tree

    def test_truncated_file_raises(self, hvd, tmp_path):
        from horovod_tpu.exceptions import CheckpointCorruptError

        path, tree = self._save(tmp_path)
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:-10])
        with pytest.raises(CheckpointCorruptError) as ei:
            checkpoint.restore(path, tree, broadcast=False)
        assert "truncated or torn" in str(ei.value)

    def test_bitflip_names_offending_leaf(self, hvd, tmp_path):
        from horovod_tpu.exceptions import CheckpointCorruptError

        path, tree = self._save(tmp_path)
        blob = bytearray(open(path, "rb").read())
        # flip one byte inside w's payload: msgpack still decodes, so
        # the error narrows the damage down to the leaf
        off = bytes(blob).index(
            np.asarray(tree["params"]["w"]).tobytes()) + 5
        blob[off] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        with pytest.raises(CheckpointCorruptError) as ei:
            checkpoint.restore(path, tree, broadcast=False)
        assert ei.value.leaf == "params/w"
        assert "params/w" in str(ei.value)

    def test_unverified_restore_still_decodes(self, hvd, tmp_path):
        """verify=False opts out (the pre-PR-9 behavior) — damage that
        happens to decode flows through, proving the sidecar check is
        what raised above."""
        path, tree = self._save(tmp_path)
        blob = bytearray(open(path, "rb").read())
        off = bytes(blob).index(
            np.asarray(tree["params"]["w"]).tobytes()) + 5
        blob[off] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        restored = checkpoint.restore(path, tree, broadcast=False,
                                      verify=False)
        assert restored["params"]["w"].shape == (64,)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_checkpoint_loads_without_framework(hvd, tmp_path):
    """Checkpoints contain no framework objects: a process that never
    imports horovod_tpu can read them with flax alone (reference contrast:
    docs/inference.rst — reference checkpoints embed HorovodAllreduce ops
    and need graph surgery before inference; here there is nothing to
    strip, docs/inference.md)."""
    import subprocess
    import sys

    import jax.numpy as jnp

    from horovod_tpu import checkpoint

    d = str(tmp_path / "ckpts")
    tree = {"params": {"w": jnp.arange(6, dtype=jnp.float32)},
            "step_count": jnp.int32(7)}
    checkpoint.save(d, tree, step=2)

    probe = (
        "import sys\n"
        "import flax.serialization\n"
        f"blob = open(r'{d}/ckpt_2.msgpack', 'rb').read()\n"
        "tree = flax.serialization.msgpack_restore(blob)\n"
        "assert 'horovod_tpu' not in sys.modules\n"
        "assert list(tree['params']['w']) == [0, 1, 2, 3, 4, 5]\n"
        "print('NO-FRAMEWORK-OK')\n"
    )
    out = subprocess.run([sys.executable, "-c", probe],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "NO-FRAMEWORK-OK" in out.stdout
