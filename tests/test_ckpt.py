"""Crash-consistent sharded checkpointing (horovod_tpu.ckpt): shard
container integrity, two-phase commit + GC, tmp hygiene keyed on writer
liveness, replica fallback, and world-size-change restore."""

import os
import subprocess

import numpy as np
import pytest

from horovod_tpu.ckpt import io as ckpt_io
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import restore as rst
from horovod_tpu.ckpt import writer as wr
from horovod_tpu.exceptions import CheckpointCorruptError


def _write(path, blob):
    with open(path, "wb") as f:
        f.write(blob)


# ---------------------------------------------------------------------------
# Shard container
# ---------------------------------------------------------------------------

class TestShardContainer:
    def _entries(self):
        return [
            mf.array_entry("params/0", np.arange(5, dtype=np.float32)),
            mf.array_entry("params/1", np.int32(7),
                           role=mf.ROLE_REPLICATED),
            mf.object_entry("meta/2", {"epoch": 3}, role=mf.ROLE_REPLICA,
                            replica_of=1),
        ]

    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "s.hvd")
        _write(path, mf.pack_shard(self._entries(),
                                   meta={"step": 4, "rank": 0}))
        meta, entries = mf.read_shard(path)
        assert meta["step"] == 4
        assert [e["key"] for e in entries] == \
            ["params/0", "params/1", "meta/2"]
        np.testing.assert_array_equal(
            entries[0]["value"], np.arange(5, dtype=np.float32))
        assert entries[0]["value"].dtype == np.float32
        assert entries[1]["value"] == np.int32(7)
        assert entries[1]["role"] == mf.ROLE_REPLICATED
        assert entries[2]["value"] == {"epoch": 3}
        assert entries[2]["replica_of"] == 1

    def test_bitflip_names_offending_leaf(self, tmp_path):
        path = str(tmp_path / "s.hvd")
        blob = bytearray(mf.pack_shard(self._entries(),
                                       meta={"step": 1}))
        # last byte sits in the final leaf's payload
        blob[-1] ^= 0xFF
        _write(path, bytes(blob))
        with pytest.raises(CheckpointCorruptError) as ei:
            mf.read_shard(path)
        assert ei.value.leaf == "meta/2"
        assert path in str(ei.value)

    def test_truncation_detected(self, tmp_path):
        path = str(tmp_path / "s.hvd")
        blob = mf.pack_shard(self._entries(), meta={"step": 1})
        _write(path, blob[:-3])
        with pytest.raises(CheckpointCorruptError) as ei:
            mf.read_shard(path)
        assert "truncated" in str(ei.value)
        assert ei.value.leaf == "meta/2"

    def test_bad_magic(self, tmp_path):
        path = str(tmp_path / "s.hvd")
        _write(path, b"not a shard container at all")
        with pytest.raises(CheckpointCorruptError) as ei:
            mf.read_shard(path)
        assert ei.value.leaf is None

    def test_verify_manifest_files_catches_rewrite(self, tmp_path):
        d = str(tmp_path)
        blob = mf.pack_shard(self._entries(), meta={"step": 1})
        name = mf.shard_name(1, 0, 1)
        _write(os.path.join(d, name), blob)
        manifest = mf.build_manifest(
            1, 0, 1, [{"rank": 0, "file": name, "bytes": len(blob),
                       "crc": ckpt_io.checksum(blob)}], {})
        mf.write_manifest(d, manifest)
        mf.verify_manifest_files(d, mf.load_manifest(d, 1))
        _write(os.path.join(d, name), blob[:-1])
        with pytest.raises(CheckpointCorruptError):
            mf.verify_manifest_files(d, mf.load_manifest(d, 1))


# ---------------------------------------------------------------------------
# Tmp hygiene: staleness keyed on writer liveness, not mtime
# ---------------------------------------------------------------------------

class TestTmpHygiene:
    def test_live_writers_old_tmp_survives(self, tmp_path):
        # regression: the pre-PR-9 mtime-only rule let a peer with a
        # skewed clock delete a LIVE writer's in-flight tmp
        d = str(tmp_path)
        fd, tmp = ckpt_io.make_tmp(d)
        os.close(fd)
        os.utime(tmp, (1.0, 1.0))  # looks hours stale by mtime
        assert ckpt_io.clean_stale_tmps(d) == 0
        assert os.path.exists(tmp)

    def test_dead_writers_fresh_tmp_removed(self, tmp_path):
        d = str(tmp_path)
        proc = subprocess.Popen(["sleep", "0"])
        proc.wait()
        tmp = os.path.join(
            d, f"ckpt.{ckpt_io.hostname()}.{proc.pid}.x1y2.tmp")
        _write(tmp, b"torn")
        assert ckpt_io.clean_stale_tmps(d) == 1
        assert not os.path.exists(tmp)

    def test_foreign_host_tmp_falls_back_to_mtime(self, tmp_path):
        d = str(tmp_path)
        tmp = os.path.join(d, f"ckpt.elsewhere.{os.getpid()}.ab.tmp")
        _write(tmp, b"torn")
        assert ckpt_io.clean_stale_tmps(d) == 0  # fresh: kept
        os.utime(tmp, (1.0, 1.0))
        assert ckpt_io.clean_stale_tmps(d) == 1  # stale: removed

    def test_parse_tmp_writer(self):
        host, pid = ckpt_io.parse_tmp_writer("base.myhost.123.r4nd.tmp")
        assert (host, pid) == ("myhost", 123)
        assert ckpt_io.parse_tmp_writer("legacy.tmp") == (None, None)
        assert ckpt_io.parse_tmp_writer("a.b.notanint.c.tmp") == \
            (None, None)
        assert ckpt_io.parse_tmp_writer("published.hvd") == (None, None)


# ---------------------------------------------------------------------------
# HOROVOD_CKPT_FAULT parser
# ---------------------------------------------------------------------------

class TestParseFault:
    def test_full_spec(self):
        spec = wr.parse_fault("kill:rank=2:phase=publish:step=7:code=19")
        assert spec == wr.FaultSpec(rank=2, phase="publish", step=7,
                                    code=19)

    def test_defaults(self):
        spec = wr.parse_fault("kill:rank=0:phase=stage")
        assert spec.step is None and spec.code == 1

    def test_empty_disarms(self):
        assert wr.parse_fault("") is None
        assert wr.parse_fault(None) is None

    def test_rejects_bad_specs(self):
        with pytest.raises(ValueError):
            wr.parse_fault("pause:rank=0:phase=stage")
        with pytest.raises(ValueError):
            wr.parse_fault("kill:rank=0")
        with pytest.raises(ValueError):
            wr.parse_fault("kill:rank=0:phase=flush")


# ---------------------------------------------------------------------------
# Two-phase commit, single-writer world
# ---------------------------------------------------------------------------

def _trees(scale):
    return {"params": {"w": np.full((6,), float(scale), np.float32),
                       "b": np.float32(scale)},
            "extra": None}


def _target():
    return {"params": {"w": np.zeros((6,), np.float32),
                       "b": np.float32(0)},
            "extra": None}


class TestCommitRestore:
    def test_default_world_is_process_topology(self, hvd, tmp_path):
        # an initialized single-process 8-device mesh is ONE writer:
        # commit() with defaulted rank/world must publish as world 1
        # immediately, not await 7 shard files no other process will
        # ever write (and abandon at the barrier timeout)
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=4,
                                   barrier_timeout=5.0)
        mgr.commit(_trees(1), step=1, generation=0)
        mgr.close()
        assert mf.all_steps(d) == [1]
        assert mf.load_manifest(d, 1)["world"] == 1

    def test_commit_restore_roundtrip(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=4)
        mgr.commit(_trees(1), step=1, generation=0, rank=0, world=1)
        mgr.commit(_trees(2), step=2, generation=0, rank=0, world=1)
        mgr.close()
        assert mf.all_steps(d) == [1, 2]
        trees, step = rst.restore_latest(d, _target())
        assert step == 2
        np.testing.assert_array_equal(
            trees["params"]["w"], np.full((6,), 2.0, np.float32))
        assert float(trees["params"]["b"]) == 2.0
        assert trees["extra"] is None

    def test_gc_keeps_last_k(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=2)
        for s in (1, 2, 3, 4):
            mgr.commit(_trees(s), step=s, generation=0, rank=0, world=1)
        mgr.close()
        assert mf.all_steps(d) == [3, 4]
        assert not os.path.exists(
            os.path.join(d, mf.shard_name(1, 0, 1)))

    def test_async_commit_flushes_on_wait(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=True, keep=2)
        mgr.commit(_trees(5), step=5, generation=0, rank=0, world=1)
        mgr.wait()
        mgr.close()
        trees, step = rst.restore_latest(d, _target())
        assert step == 5
        np.testing.assert_array_equal(
            trees["params"]["w"], np.full((6,), 5.0, np.float32))

    def test_torn_newest_falls_back_to_previous(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=4)
        mgr.commit(_trees(1), step=1, generation=0, rank=0, world=1)
        mgr.commit(_trees(2), step=2, generation=0, rank=0, world=1)
        mgr.close()
        shard2 = os.path.join(d, mf.shard_name(2, 0, 1))
        blob = bytearray(open(shard2, "rb").read())
        blob[-1] ^= 0xFF
        _write(shard2, bytes(blob))
        trees, step = rst.restore_latest(d, _target())
        assert step == 1  # damaged cut skipped, previous restored
        np.testing.assert_array_equal(
            trees["params"]["w"], np.full((6,), 1.0, np.float32))
        # every published cut damaged -> loud failure, not silent zeros
        shard1 = os.path.join(d, mf.shard_name(1, 0, 1))
        _write(shard1, b"")
        with pytest.raises(CheckpointCorruptError):
            rst.restore_latest(d, _target())

    def test_staged_tmp_invisible_to_restore(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=4)
        mgr.commit(_trees(1), step=1, generation=0, rank=0, world=1)
        mgr.close()
        fd, tmp = ckpt_io.make_tmp(d, base=mf.shard_name(2, 0, 1))
        with os.fdopen(fd, "wb") as f:
            f.write(b"half a shard, writer died here")
        assert mf.all_steps(d) == [1]
        trees, step = rst.restore_latest(d, _target())
        assert step == 1

    def test_restore_empty_dir(self, tmp_path):
        trees, step = rst.restore_latest(str(tmp_path), _target())
        assert trees is None and step is None

    def test_structure_change_is_loud(self, tmp_path):
        d = str(tmp_path)
        mgr = wr.CheckpointManager(d, async_write=False, keep=4)
        mgr.commit(_trees(1), step=1, generation=0, rank=0, world=1)
        mgr.close()
        target = {"params": {"w": np.zeros((6,), np.float32),
                             "b": np.float32(0),
                             "new_leaf": np.zeros((2,), np.float32)},
                  "extra": None}
        with pytest.raises(CheckpointCorruptError):
            rst.restore_step(d, 1, target)


# ---------------------------------------------------------------------------
# Replica fallback: a damaged shard file restores from its left
# neighbor's replica section
# ---------------------------------------------------------------------------

class TestReplicaFallback:
    def _publish_world2(self, d):
        """Hand-build a 2-rank checkpoint where rank 0's file also
        carries rank 1's bytes as replica entries (what the neighbor
        ring produces)."""
        w0 = np.arange(4, dtype=np.float32)
        w1 = np.arange(4, dtype=np.float32) * 10
        shards = []
        for rank, entries in (
            (0, [mf.array_entry("params/0", w0,
                                role=mf.ROLE_REPLICATED),
                 mf.array_entry("params/1", w1, role=mf.ROLE_REPLICA,
                                replica_of=1)]),
            (1, [mf.array_entry("params/1", w1,
                                role=mf.ROLE_REPLICATED)]),
        ):
            blob = mf.pack_shard(entries, meta={"step": 3, "rank": rank})
            name = mf.shard_name(3, rank, 2)
            _write(os.path.join(d, name), blob)
            shards.append({"rank": rank, "file": name,
                           "bytes": len(blob),
                           "crc": ckpt_io.checksum(blob)})
        mf.write_manifest(d, mf.build_manifest(3, 0, 2, shards, {}))
        return w0, w1

    def test_missing_shard_recovered_from_replica(self, tmp_path):
        from horovod_tpu.ckpt import stats

        d = str(tmp_path)
        w0, w1 = self._publish_world2(d)
        os.unlink(os.path.join(d, mf.shard_name(3, 1, 2)))
        before = stats.REPLICA_RESTORES.value
        target = {"params": {"a": np.zeros(4, np.float32),
                             "b": np.zeros(4, np.float32)}}
        trees, step = rst.restore_step(d, 3, target)
        assert step == 3
        np.testing.assert_array_equal(trees["params"]["a"], w0)
        np.testing.assert_array_equal(trees["params"]["b"], w1)
        assert stats.REPLICA_RESTORES.value == before + 1

    def test_unrecoverable_without_replica(self, tmp_path):
        d = str(tmp_path)
        self._publish_world2(d)
        # rank 0's file is the one carrying the replica: losing IT
        # leaves params/0 with no copy anywhere
        os.unlink(os.path.join(d, mf.shard_name(3, 0, 2)))
        target = {"params": {"a": np.zeros(4, np.float32),
                             "b": np.zeros(4, np.float32)}}
        with pytest.raises(CheckpointCorruptError):
            rst.restore_step(d, 3, target)


# ---------------------------------------------------------------------------
# World-size-change restore: re-flatten + re-scatter sharded state
# ---------------------------------------------------------------------------

class TestWorldChange:
    N = 10  # real elements; pads differently under world 2 and 3

    def _state(self, world, rank, shard_elems, fill=None):
        from horovod_tpu.parallel import zero

        g = zero.GroupSpec(dtype=np.dtype(np.float32).str, indices=(0,),
                           shapes=((self.N,),), sizes=(self.N,),
                           n=self.N, shard_elems=shard_elems,
                           padded=shard_elems * world)
        spec = zero.ZeroSpec(groups=(g,), world=world, rank=rank,
                             num_leaves=1)
        if fill is None:
            seg = np.zeros((shard_elems,), np.float32)
            return zero.FlatAdamState(
                spec=spec, count=np.int32(0), master=(seg,),
                mu=(seg.copy(),), nu=(seg.copy(),))
        lo = rank * shard_elems
        full = np.zeros((shard_elems * world,), np.float32)
        full[:self.N] = fill
        seg = full[lo:lo + shard_elems]
        return zero.FlatAdamState(
            spec=spec, count=np.int32(9), master=(seg.copy(),),
            mu=(seg.copy() * 2,), nu=(seg.copy() * 3,))

    def test_restore_world2_into_world3(self, tmp_path):
        d = str(tmp_path)
        fill = np.arange(self.N, dtype=np.float32) + 1
        # world 2 commits (shard_elems 6): rank 1 first, then the
        # leader finds both files via the shared-fs fallback
        for rank in (1, 0):
            mgr = wr.CheckpointManager(d, async_write=False, keep=2,
                                       barrier_timeout=5.0)
            mgr.commit({"opt": self._state(2, rank, 6, fill=fill)},
                       step=1, generation=0, rank=rank, world=2)
            mgr.close()
        manifest = mf.load_manifest(d, 1)
        assert manifest["world"] == 2
        assert manifest["sharded"]["opt/0"]["groups"][0][1] == self.N
        # restore every rank of a world-3 job (shard_elems 4)
        seen = {"master": [], "mu": [], "nu": []}
        for new_rank in range(3):
            target = {"opt": self._state(3, new_rank, 4)}
            trees, step = rst.restore_step(d, 1, target)
            assert step == 1
            got = trees["opt"]
            assert int(got.count) == 9
            assert got.spec.world == 3 and got.spec.rank == new_rank
            for comp in seen:
                arr = np.asarray(getattr(got, comp)[0])
                assert arr.shape == (4,)
                seen[comp].append(arr)
        for comp, scale in (("master", 1), ("mu", 2), ("nu", 3)):
            full_new = np.concatenate(seen[comp])[:self.N]
            np.testing.assert_array_equal(full_new, fill * scale)


# ---------------------------------------------------------------------------
# World-size-change restore for ZeRO-2 gradient shards and ZeRO-3
# parameter shards, including the neighbor-replica fallback
# ---------------------------------------------------------------------------

class TestWorldChangeZeRO23:
    N = 10  # pads to 12 under both world 2 (shard 6) and world 3 (shard 4)

    def _spec(self, world, rank, shard_elems):
        from horovod_tpu.parallel import zero

        g = zero.GroupSpec(dtype=np.dtype(np.float32).str, indices=(0,),
                           shapes=((self.N,),), sizes=(self.N,),
                           n=self.N, shard_elems=shard_elems,
                           padded=shard_elems * world)
        return zero.ZeroSpec(groups=(g,), world=world, rank=rank,
                             num_leaves=1)

    def _seg(self, world, rank, shard_elems, fill):
        full = np.zeros((shard_elems * world,), np.float32)
        if fill is not None:
            full[:self.N] = fill
        lo = rank * shard_elems
        return full[lo:lo + shard_elems].copy()

    def _params(self, world, rank, shard_elems, fill=None):
        import jax

        from horovod_tpu.parallel import zero

        treedef = jax.tree_util.tree_structure({"w": 0})
        return zero.ShardedParams(
            self._spec(world, rank, shard_elems), treedef,
            (self._seg(world, rank, shard_elems, fill),))

    def _grads(self, world, rank, shard_elems, fill=None):
        from horovod_tpu.parallel import zero

        return zero.ShardedGrads(
            self._spec(world, rank, shard_elems),
            (self._seg(world, rank, shard_elems, fill),))

    def test_restore_world2_into_world3(self, tmp_path):
        d = str(tmp_path)
        p_fill = np.arange(self.N, dtype=np.float32) + 1
        g_fill = -(np.arange(self.N, dtype=np.float32) + 1) / 4
        for rank in (1, 0):
            mgr = wr.CheckpointManager(d, async_write=False, keep=2,
                                       barrier_timeout=5.0)
            mgr.commit({"grads": self._grads(2, rank, 6, fill=g_fill),
                        "params": self._params(2, rank, 6, fill=p_fill)},
                       step=1, generation=0, rank=rank, world=2)
            mgr.close()
        manifest = mf.load_manifest(d, 1)
        assert manifest["world"] == 2
        assert manifest["sharded"]["grads/0"]["kind"] == "sharded_grads"
        assert manifest["sharded"]["params/1"]["kind"] == "sharded_params"
        seen = {"params": [], "grads": []}
        for new_rank in range(3):
            target = {"grads": self._grads(3, new_rank, 4),
                      "params": self._params(3, new_rank, 4)}
            trees, step = rst.restore_step(d, 1, target)
            assert step == 1
            for name in seen:
                got = trees[name]
                assert got.spec.world == 3
                assert got.spec.rank == new_rank
                arr = np.asarray(got.shards[0])
                assert arr.shape == (4,)
                seen[name].append(arr)
        np.testing.assert_array_equal(
            np.concatenate(seen["params"])[:self.N], p_fill)
        np.testing.assert_array_equal(
            np.concatenate(seen["grads"])[:self.N], g_fill)

    def _publish_world2_with_replica(self, d, fill):
        """Hand-build a 2-rank stage-3 checkpoint where rank 0's file
        also carries rank 1's parameter-shard segment as a replica
        entry (what the neighbor ring produces for sharded leaves)."""
        segs = [self._seg(2, r, 6, fill) for r in range(2)]
        shards = []
        for rank, entries in (
            (0, [mf.array_entry("params/0#leaf/0", segs[0],
                                role=mf.ROLE_OWN),
                 mf.array_entry("params/0#leaf/0", segs[1],
                                role=mf.ROLE_REPLICA, replica_of=1)]),
            (1, [mf.array_entry("params/0#leaf/0", segs[1],
                                role=mf.ROLE_OWN)]),
        ):
            blob = mf.pack_shard(entries, meta={"step": 5, "rank": rank})
            name = mf.shard_name(5, rank, 2)
            _write(os.path.join(d, name), blob)
            shards.append({"rank": rank, "file": name,
                           "bytes": len(blob),
                           "crc": ckpt_io.checksum(blob)})
        layout = {"params/0": {
            "kind": "sharded_params", "world": 2,
            "groups": [[np.dtype(np.float32).str, self.N, 6, 12]]}}
        mf.write_manifest(d, mf.build_manifest(5, 0, 2, shards, layout))

    def test_param_shard_recovered_from_replica(self, tmp_path):
        from horovod_tpu.ckpt import stats

        d = str(tmp_path)
        fill = np.arange(self.N, dtype=np.float32) * 3 + 1
        self._publish_world2_with_replica(d, fill)
        os.unlink(os.path.join(d, mf.shard_name(5, 1, 2)))
        before = stats.REPLICA_RESTORES.value
        seen = []
        for new_rank in range(3):
            target = {"params": self._params(3, new_rank, 4)}
            trees, step = rst.restore_step(d, 5, target)
            assert step == 5
            seen.append(np.asarray(trees["params"].shards[0]))
        np.testing.assert_array_equal(
            np.concatenate(seen)[:self.N], fill)
        assert stats.REPLICA_RESTORES.value == before + 3

    def test_param_shard_unrecoverable_without_replica(self, tmp_path):
        d = str(tmp_path)
        fill = np.arange(self.N, dtype=np.float32)
        self._publish_world2_with_replica(d, fill)
        # rank 0's file carries both its own segment and the replica:
        # losing IT leaves rank 0's segment with no copy anywhere
        os.unlink(os.path.join(d, mf.shard_name(5, 0, 2)))
        with pytest.raises(CheckpointCorruptError):
            rst.restore_step(d, 5, {"params": self._params(2, 0, 6)})
