"""Multiprocess crash-consistency acceptance for the two-phase commit.

Fast (tier-1) cell: two real writer processes commit in lockstep over a
shared directory; rank 1 is killed by ``HOROVOD_CKPT_FAULT`` the
instant its step-2 shard is staged (tmp fsync'd, nothing published).
The survivor must abandon the step-2 commit, the step-1 manifest must
stay the newest restorable cut — bit-identical — and the dead writer's
torn tmp must be invisible to restore and reclaimable by pid-liveness.

The full kill-at-every-phase × elastic-re-form matrix (KV barrier,
neighbor-replica moment recovery with sharded AdamW) runs in
tools/chaos_matrix.py; its mid-commit cell is repeated here slow-marked
so a multi-core box exercises it under pytest too.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu.ckpt import io as ckpt_io
from horovod_tpu.ckpt import manifest as mf
from horovod_tpu.ckpt import restore as rst

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# each worker stages+publishes steps over the shared-fs fallback (no
# rendezvous KV): the leader's publish waits for every rank's shard
# file instead of the staged.<rank> barrier
_WORKER = r"""
import os, sys
import numpy as np
import horovod_tpu  # noqa: F401  (package init)
from horovod_tpu.ckpt.writer import CheckpointManager

d = sys.argv[1]
rank = int(os.environ["HOROVOD_RANK"])
mgr = CheckpointManager(d, async_write=False, keep=10,
                        barrier_timeout=3.0)
for step in (1, 2):
    trees = {"params": {"w": np.full((4,), float(step), np.float32)}}
    mgr.commit(trees, step=step, generation=0, rank=rank, world=2)
mgr.close()
print("WORKER_DONE", rank, flush=True)
"""


def test_kill_while_staging_preserves_previous_cut(tmp_path):
    d = str(tmp_path / "ckpts")
    os.makedirs(d)
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": "2",
            # staged at step 2, killed before anything is published
            "HOROVOD_CKPT_FAULT": "kill:rank=1:phase=stage:step=2:code=21",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER, d], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = {}
    for rank, proc in enumerate(procs):
        outs[rank], _ = proc.communicate(timeout=120)
    assert procs[1].returncode == 21, outs[1][-2000:]
    # the survivor abandons step 2 and exits cleanly
    assert procs[0].returncode == 0, outs[0][-2000:]
    assert "WORKER_DONE 0" in outs[0]

    # step 1 is the newest PUBLISHED cut; rank 0's orphaned step-2
    # shard file exists but no manifest names it
    assert mf.all_steps(d) == [1]
    target = {"params": {"w": np.zeros((4,), np.float32)}}
    trees, step = rst.restore_latest(d, target)
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(trees["params"]["w"]),
        np.full((4,), 1.0, np.float32))  # bit-identical

    # the dead writer's torn tmp: invisible above, reclaimed now that
    # its pid is provably gone
    tmps = [n for n in os.listdir(d) if n.endswith(".tmp")]
    assert len(tmps) == 1, tmps
    assert ckpt_io.clean_stale_tmps(d) == 1


@pytest.mark.slow
def test_chaos_matrix_ckpt_kill_mid_commit():
    """Full elastic cell: KV barrier, publish-phase kill, re-form, and
    bit-identical restore of every surviving manifest."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_matrix.py"),
         "--only", "ckpt_kill_mid_commit"],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
