"""Gradient correctness through each collective op.

Mirrors the reference's gradient registrations and their tests (reference:
horovod/tensorflow/mpi_ops.py:89-180 — grad(allreduce)=allreduce,
grad(allgather)=slice of the allreduced grad, grad(broadcast)=allreduce
zeroed off-root; tested at test/test_tensorflow.py:385-460,684-977). In
the TPU build these identities must hold for differentiation through the
in-jit collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

K = 4  # elements per device


def _x(hvd, rng):
    n = hvd.size()
    return jnp.asarray(rng.randn(n, K).astype(np.float32))


def _weights(hvd, rng, shape):
    """Per-device cotangent weights, distinct per rank."""
    n = hvd.size()
    return jnp.asarray(rng.randn(n, *shape).astype(np.float32))


def _grad_of(hvd, fn, x, w):
    """d/dx of sum over devices of <fn(x_local), w_local>."""

    def loss(x):
        def inner(x, w):
            val = jnp.sum(fn(x[0]) * w[0])
            return jax.lax.psum(val, hvd.GLOBAL_AXES)

        return jax.shard_map(
            inner, mesh=hvd.mesh(),
            in_specs=(P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
            out_specs=P(), check_vma=False)(x, w)

    return np.asarray(jax.jit(jax.grad(loss))(x))


class TestCollectiveGradients:
    def test_allreduce_grad_is_allreduced(self, hvd):
        """y = mean_j x_j  =>  dL/dx_j = (1/N) sum_i w_i (reference:
        grad(allreduce) = allreduce of the upstream grad)."""
        rng = np.random.RandomState(0)
        x, w = _x(hvd, rng), _weights(hvd, rng, (K,))
        g = _grad_of(hvd, lambda xl: hvd.allreduce(xl, average=True), x, w)
        expect = np.tile(np.asarray(w).sum(0) / hvd.size(), (hvd.size(), 1))
        np.testing.assert_allclose(g, expect, atol=1e-6)

    def test_allgather_grad_is_slice_of_reduced(self, hvd):
        """y_i = concat_j x_j  =>  dL/dx_j = sum_i w_i[slice j]
        (reference: grad(allgather) = this rank's slice of the allreduced
        grad, mpi_ops.py:120-131)."""
        rng = np.random.RandomState(1)
        n = hvd.size()
        x = _x(hvd, rng)
        w = _weights(hvd, rng, (n * K,))
        g = _grad_of(hvd, lambda xl: hvd.allgather(xl), x, w)
        summed = np.asarray(w).sum(0)  # (n*K,)
        expect = summed.reshape(n, K)
        np.testing.assert_allclose(g, expect, atol=1e-6)

    def test_broadcast_grad_zeroed_off_root(self, hvd):
        """y_i = x_root  =>  dL/dx_root = sum_i w_i, zero elsewhere
        (reference: grad(broadcast) = allreduce with non-root zeroed,
        mpi_ops.py:162-180)."""
        rng = np.random.RandomState(2)
        root = 1
        x, w = _x(hvd, rng), _weights(hvd, rng, (K,))
        g = _grad_of(
            hvd, lambda xl: hvd.broadcast(xl, root), x, w)
        expect = np.zeros_like(g)
        expect[root] = np.asarray(w).sum(0)
        np.testing.assert_allclose(g, expect, atol=1e-6)

    def test_reducescatter_grad(self, hvd):
        """y_i = (sum_j x_j)[slice i]  =>  dL/dx_j = concat_i w_i."""
        rng = np.random.RandomState(3)
        n = hvd.size()
        x = jnp.asarray(rng.randn(n, n * 2).astype(np.float32))
        w = _weights(hvd, rng, (2,))
        g = _grad_of(
            hvd, lambda xl: hvd.reducescatter(xl, op=hvd.Sum), x, w)
        expect = np.tile(np.asarray(w).reshape(-1), (n, 1))
        np.testing.assert_allclose(g, expect, atol=1e-6)
