"""Collective op tests across dtypes, eager and in-jit.

Mirrors the reference's framework op tests (reference:
test/test_tensorflow.py — test_horovod_allreduce:109-150, allgather
variable-size :546-649, error paths :314-384; test/test_torch.py).
Each test computes the collective and asserts numerical equality against a
locally computed expectation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32]


class TestAllreduce:
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_average(self, hvd, dtype):
        vals = [np.full((4, 3), i, dtype="float32") for i in range(hvd.size())]
        x = hvd.stack_per_worker([jnp.asarray(v, dtype=dtype) for v in vals])
        out = hvd.allreduce(x)  # default average=True
        expected = np.mean(np.stack(vals), axis=0)
        np.testing.assert_allclose(np.asarray(out, dtype="float32"), expected,
                                   rtol=1e-2)

    def test_sum(self, hvd):
        vals = [np.full((5,), i + 1.0, dtype="float32") for i in range(hvd.size())]
        x = hvd.stack_per_worker(vals)
        out = hvd.allreduce(x, average=False)
        np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0))

    def test_min_max_product(self, hvd):
        vals = [np.full((3,), float(i + 1), dtype="float32") for i in range(hvd.size())]
        x = hvd.stack_per_worker(vals)
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x, op=hvd.Min)), 1.0)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, op=hvd.Max)), float(hvd.size()))
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, op=hvd.Product)),
            float(np.prod(np.arange(1, hvd.size() + 1))))

    def test_replicated_input(self, hvd):
        # Every worker holds the same tensor: average is identity, sum
        # multiplies by size.
        x = jnp.ones((3, 2))
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x)), 1.0)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, average=False)), float(hvd.size()))

    def test_average_and_op_conflict(self, hvd):
        with pytest.raises(ValueError, match="average or op"):
            hvd.allreduce(jnp.ones(2), average=True, op=hvd.Sum)

    def test_result_replicated(self, hvd):
        x = hvd.stack_per_worker([np.ones((2, 2), "float32")] * hvd.size())
        out = hvd.allreduce(x)
        assert out.sharding.is_fully_replicated

    def test_fp16_compression(self, hvd):
        vals = [np.full((8,), i / 7.0, dtype="float32") for i in range(hvd.size())]
        x = hvd.stack_per_worker(vals)
        out = hvd.allreduce(x, compression=hvd.Compression.fp16)
        assert out.dtype == jnp.float32  # decompressed back
        np.testing.assert_allclose(
            np.asarray(out), np.mean(np.stack(vals), 0), rtol=1e-2)

    def test_grouped(self, hvd):
        tensors = [
            hvd.stack_per_worker([np.full((2,), i * (k + 1), "float32")
                                  for i in range(hvd.size())])
            for k in range(3)
        ]
        outs = hvd.grouped_allreduce(tensors, average=False)
        for k, out in enumerate(outs):
            expected = sum(i * (k + 1) for i in range(hvd.size()))
            np.testing.assert_allclose(np.asarray(out), expected)


class TestAllgather:
    def test_uniform(self, hvd):
        vals = [np.full((2, 3), i, "float32") for i in range(hvd.size())]
        out = hvd.allgather(hvd.stack_per_worker(vals))
        np.testing.assert_allclose(np.asarray(out), np.concatenate(vals, 0))
        assert out.shape == (2 * hvd.size(), 3)

    def test_ragged(self, hvd):
        # reference: variable-size allgather (test_tensorflow.py:546-649)
        vals = [np.full((i + 1, 2), i, "float32") for i in range(hvd.size())]
        out = hvd.allgather(vals)
        np.testing.assert_allclose(np.asarray(out), np.concatenate(vals, 0))

    def test_ragged_shape_mismatch_raises(self, hvd):
        # reference: mismatched non-first dims must error
        # (test_tensorflow.py:314-384)
        vals = [np.ones((2, 3), "float32") for _ in range(hvd.size())]
        vals[1] = np.ones((2, 4), "float32")
        with pytest.raises(ValueError, match="match in all but the first"):
            hvd.allgather(vals)

    def test_ragged_wrong_count_raises(self, hvd):
        with pytest.raises(ValueError, match="one tensor per worker"):
            hvd.allgather([np.ones((1,), "float32")] * (hvd.size() - 1))


class TestBroadcast:
    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_broadcast(self, hvd, root):
        vals = [np.full((4,), i, "float32") for i in range(hvd.size())]
        out = hvd.broadcast(hvd.stack_per_worker(vals), root_rank=root)
        np.testing.assert_allclose(np.asarray(out), vals[root])

    def test_bad_root(self, hvd):
        with pytest.raises(ValueError, match="out of range"):
            hvd.broadcast(jnp.ones(2), root_rank=99)

    def test_replicated_identity(self, hvd):
        x = jnp.arange(6.0)
        np.testing.assert_allclose(np.asarray(hvd.broadcast(x, 0)),
                                   np.arange(6.0))


class TestReducescatter:
    def test_sum(self, hvd):
        w = hvd.size()
        vals = [np.arange(w * 2, dtype="float32") + i for i in range(w)]
        out = hvd.reducescatter(hvd.stack_per_worker(vals), average=False)
        full = np.sum(np.stack(vals), 0)
        np.testing.assert_allclose(
            np.asarray(out), full.reshape(w, 2))

    def test_indivisible_raises(self, hvd):
        x = hvd.stack_per_worker(
            [np.ones((3,), "float32")] * hvd.size())
        with pytest.raises(ValueError, match="divide evenly"):
            hvd.reducescatter(x)


class TestAlltoall:
    def test_transpose_blocks(self, hvd):
        w = hvd.size()
        # worker i sends value i*w+j to worker j
        vals = [np.arange(i * w, (i + 1) * w, dtype="float32") for i in range(w)]
        out = hvd.alltoall(hvd.stack_per_worker(vals))
        result = np.asarray(out)
        # worker j receives [i*w+j for all i]
        for j in range(w):
            np.testing.assert_allclose(result[j], np.arange(w) * w + j)


class TestInJit:
    """In-jit collectives under shard_map — the hot path."""

    def test_psum_allreduce(self, hvd):
        mesh = hvd.mesh()

        def f(x):
            return hvd.allreduce(x, average=False)

        x = jnp.arange(8.0)
        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
                          out_specs=P())
        )(x)
        np.testing.assert_allclose(np.asarray(out), [28.0])

    def test_pmean_allreduce(self, hvd):
        mesh = hvd.mesh()

        def f(x):
            return hvd.allreduce(x)

        x = jnp.arange(8.0)
        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
                          out_specs=P())
        )(x)
        np.testing.assert_allclose(np.asarray(out), [3.5])

    def test_all_gather(self, hvd):
        mesh = hvd.mesh()

        def f(x):
            return hvd.allgather(x)

        x = jnp.arange(8.0)
        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
                          out_specs=P(hvd.GLOBAL_AXES))
        )(x)
        # every worker holds the full concatenation
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(np.arange(8.0), 8))

    def test_broadcast_in_jit(self, hvd):
        mesh = hvd.mesh()

        def f(x):
            return hvd.broadcast(x, root_rank=5)

        x = jnp.arange(8.0)
        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
                          out_specs=P())
        )(x)
        np.testing.assert_allclose(np.asarray(out), [5.0])


class TestInJitEdgeCases:
    def test_product_with_negatives_and_zeros(self, hvd):
        mesh = hvd.mesh()

        def f(x):
            return hvd.allreduce(x, op=hvd.Product)

        run = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
                                    out_specs=P()))
        vals = np.array([-2.0, 3.0, 1.0, -1.0, 2.0, 1.0, 1.0, 1.0], "float32")
        np.testing.assert_allclose(np.asarray(run(jnp.asarray(vals))),
                                   [np.prod(vals)], rtol=1e-5)
        vals_zero = np.array([-2.0, 0.0, 3.0, 1.0, 1.0, 1.0, 1.0, 1.0], "float32")
        np.testing.assert_allclose(np.asarray(run(jnp.asarray(vals_zero))),
                                   [0.0])

    def test_reducescatter_min_max_product_in_jit(self, hvd):
        """psum_scatter is sum-only; min/max/product decompose into
        all_to_all + local reduce. Each device contributes (8,) = 8
        devices x shard 1; device d's output is op over all devices'
        element d."""
        mesh = hvd.mesh()
        rng = np.random.RandomState(7)
        per_dev = rng.randint(1, 5, size=(8, 8)).astype(np.float32)

        for op, npop in [(hvd.Min, np.min), (hvd.Max, np.max),
                         (hvd.Product, np.prod)]:
            def f(x, _op=op):
                return hvd.reducescatter(x, op=_op)

            x = jnp.asarray(per_dev.reshape(-1))  # (64,) -> (8,)/device
            out = jax.jit(jax.shard_map(
                f, mesh=mesh,
                in_specs=P(hvd.GLOBAL_AXES),
                out_specs=P(hvd.GLOBAL_AXES)))(x)
            np.testing.assert_allclose(
                np.asarray(out), npop(per_dev, axis=0), rtol=1e-6)

    def test_reducescatter_min_subaxis(self, hvd):
        """Pin the all_to_all shard placement on a PARTIAL axis: min over
        the 'local' axis (size 4) of the 2x4 mesh, with a trailing dim.
        data[g, j, d, :] = local device (g, j)'s row d; device (g, d)
        must end up with min over j of data[g, j, d, :]."""
        mesh = hvd.mesh()
        rng = np.random.RandomState(11)
        data = rng.randint(0, 9, size=(2, 4, 4, 3)).astype(np.float32)

        def f(x):  # per-device (4, 3): rows scatter over the local axis
            return hvd.reducescatter(x, op=hvd.Min,
                                     axis_name=hvd.LOCAL_AXIS)

        x = jnp.asarray(data.reshape(32, 3))
        out = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=P(hvd.GLOBAL_AXES),
            out_specs=P(hvd.GLOBAL_AXES)))(x)
        np.testing.assert_allclose(
            np.asarray(out).reshape(2, 4, 3), np.min(data, axis=1),
            rtol=1e-6)

    def test_reducescatter_average_subaxis(self, hvd):
        # average over the 'local' axis only must divide by local_size (4),
        # not the global size (8).
        mesh = hvd.mesh()

        def f(x):
            return hvd.reducescatter(x, average=True, axis_name=hvd.LOCAL_AXIS)

        x = jnp.ones((32,))  # per-device (4,) after sharding
        out = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=P(hvd.GLOBAL_AXES),
            out_specs=P(hvd.GLOBAL_AXES)))(x)
        # sum over 4 local devices = 4.0; averaging must divide by 4 -> 1.0
        np.testing.assert_allclose(np.asarray(out), np.ones((8,)))


class TestRankGuards:
    def test_allgather_scalar_per_worker_raises(self, hvd):
        x = hvd.stack_per_worker(np.arange(8, dtype="float32"))
        with pytest.raises(ValueError, match="rank >= 1"):
            hvd.allgather(x)

    def test_alltoall_scalar_per_worker_raises(self, hvd):
        x = hvd.stack_per_worker(np.arange(8, dtype="float32"))
        with pytest.raises(ValueError, match="rank >= 2"):
            hvd.alltoall(x)

    def test_reducescatter_scalar_per_worker_raises(self, hvd):
        x = hvd.stack_per_worker(np.arange(8, dtype="float32"))
        with pytest.raises(ValueError, match="rank >= 2"):
            hvd.reducescatter(x)


class TestSingleWorkerSemantics:
    """A 1-device world must not squeeze user arrays whose leading dim
    happens to equal size (regression for the size==1 stacked ambiguity)."""

    def test_leading_dim_one_preserved(self):
        import jax as _jax
        import horovod_tpu as hvd

        hvd.shutdown()
        hvd.init(devices=_jax.devices()[:1], mesh_shape=(1, 1))
        assert hvd.size() == 1
        x = jnp.ones((1, 5))
        out = hvd.allreduce(x)
        assert out.shape == (1, 5)
        out_b = hvd.broadcast(jnp.ones((1, 4)), root_rank=0)
        assert out_b.shape == (1, 4)
        # explicit stacked encoding still reduces away the worker axis
        stacked = hvd.stack_per_worker(jnp.ones((1, 3)))
        assert hvd.allreduce(stacked).shape == (3,)
        hvd.shutdown()


class TestBroadcastReplication:
    def test_broadcast_forces_replicated_layout(self, hvd):
        # non-stacked input gets the replicated mesh sharding, honoring the
        # broadcast_parameters contract
        x = jnp.ones((4, 2))
        out = hvd.broadcast(x, root_rank=0)
        assert out.sharding.is_fully_replicated
        assert len(out.sharding.device_set) == 8


class TestAsyncHandles:
    """reference: horovod/torch/mpi_ops.py poll/synchronize (:93-124)."""

    def test_allreduce_async(self, hvd):
        vals = [np.full((4,), i, "float32") for i in range(hvd.size())]
        handle = hvd.allreduce_async(hvd.stack_per_worker(vals), average=False)
        out = hvd.synchronize(handle)
        np.testing.assert_allclose(np.asarray(out), np.sum(np.stack(vals), 0))
        assert hvd.poll(handle)

    def test_poll_propagates_errors(self):
        """An error raised inside is_ready() must surface to the poll()
        caller — not be reported as 'complete' only to raise from an
        unrelated wait() later."""
        from horovod_tpu.ops.collectives import Handle

        class Poisoned:
            def is_ready(self):
                raise RuntimeError("device poisoned")

        with pytest.raises(RuntimeError, match="device poisoned"):
            Handle(Poisoned()).poll()

    def test_multiple_in_flight(self, hvd):
        handles = [
            hvd.allreduce_async(
                hvd.stack_per_worker(
                    [np.full((2,), i * k, "float32") for i in range(hvd.size())]),
                average=False)
            for k in range(5)
        ]
        for k, h in enumerate(handles):
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(h)),
                sum(i * k for i in range(hvd.size())))


class TestReviewRegressions:
    def test_int_product_exact_in_jit(self, hvd_flat):
        """Integer Product must be exact past 2^24 (fp32 log-sum-exp
        rounds; the reference's MPI_PROD is exact)."""
        from jax.sharding import PartitionSpec as P

        vals = np.ones((8,), np.int32)
        vals[0], vals[1] = 5003, 4999

        def per_device(x):
            return hvd_flat.allreduce(x[0], op=hvd_flat.Product)

        # check_vma on: the result must be statically replicated
        out = jax.jit(jax.shard_map(
            per_device, mesh=hvd_flat.mesh(),
            in_specs=P("local"), out_specs=P()))(jnp.asarray(vals))
        assert int(out) == 5003 * 4999

    def test_bool_broadcast_preserves_dtype_in_jit(self, hvd_flat):
        from jax.sharding import PartitionSpec as P

        masks = np.zeros((8, 4), bool)
        masks[2] = [True, False, True, True]

        def per_device(x):
            return hvd_flat.broadcast(x[0], root_rank=2)

        out = jax.jit(jax.shard_map(
            per_device, mesh=hvd_flat.mesh(),
            in_specs=P("local"), out_specs=P(), check_vma=False))(
            jnp.asarray(masks))
        assert out.dtype == jnp.bool_
        np.testing.assert_array_equal(np.asarray(out), masks[2])

    def test_grouped_allreduce_fused_matches_individual(self, hvd_flat):
        n = hvd_flat.size()
        rng = np.random.RandomState(0)
        tensors = [
            hvd_flat.stack_per_worker(
                [rng.rand(3, 2).astype(np.float32) for _ in range(n)]),
            hvd_flat.stack_per_worker(
                [rng.rand(5).astype(np.float32) for _ in range(n)]),
            hvd_flat.stack_per_worker(
                [rng.randint(0, 9, (4,)).astype(np.int32)
                 for _ in range(n)]),
        ]
        grouped = hvd_flat.grouped_allreduce(tensors, op=hvd_flat.Sum)
        individual = [hvd_flat.allreduce(t, op=hvd_flat.Sum)
                      for t in tensors]
        for g, ind in zip(grouped, individual):
            assert g.shape == ind.shape and g.dtype == ind.dtype
            np.testing.assert_allclose(np.asarray(g), np.asarray(ind),
                                       rtol=1e-6)
