"""Collective transport observatory (ISSUE 16): busbw math, rolling
windows, the degradation detector's latch/re-arm contract, the surfaces
(/comms route, merged-trace counter track, hvd_top panel, cross-rank
postmortem report), and the persisted probe roofline artifact.

Tier-1 safe: everything here drives the tracker and probe cache directly
— no devices, no subprocesses (the 2-rank netdelay acceptance lives in
test_multiprocess.py::test_comms_degradation_alert_under_netdelay).
"""

import json
import os
import sys
import types
import urllib.request

import pytest

from horovod_tpu import comms, flight_recorder


@pytest.fixture
def tracker():
    """A fresh CommsTracker so tests never fight the process singleton."""
    t = comms.CommsTracker()
    t.world = 2
    yield t


@pytest.fixture
def singleton():
    """The process-wide tracker, state-restored after the test (the
    /comms route and bench comms_rows read through the singleton)."""
    t = comms.tracker()
    with t._lock:
        saved_roof = (dict(t._roofline), dict(t._roofline_source))
        saved = (t.enabled, t.rank, t.world)
    t.reset()
    t.enabled = True
    yield t
    t.reset()
    with t._lock:
        t._roofline.clear()
        t._roofline.update(saved_roof[0])
        t._roofline_source.clear()
        t._roofline_source.update(saved_roof[1])
    t.enabled, t.rank, t.world = saved


def _degraded_events(lane):
    return [e for e in flight_recorder.recorder().events()
            if e.get("kind") == "comms_degraded" and e.get("lane") == lane]


def _recovered_events(lane):
    return [e for e in flight_recorder.recorder().events()
            if e.get("kind") == "comms_recovered" and e.get("lane") == lane]


class TestBusFactor:
    @pytest.mark.parametrize("op,world,factor", [
        # the NCCL-tests convention
        ("allreduce", 2, 1.0),
        ("allreduce", 4, 1.5),
        ("allreduce", 8, 2 * 7 / 8),
        ("reducescatter", 4, 0.75),
        ("allgather", 4, 0.75),
        ("alltoall", 8, 7 / 8),
        ("broadcast", 4, 1.0),
        ("get", 4, 1.0),   # kv point-to-point
        # world=1 degenerate: a one-rank collective moves nothing
        # across any bus — factor 0 for EVERY op
        ("allreduce", 1, 0.0),
        ("allgather", 1, 0.0),
        ("broadcast", 1, 0.0),
        ("allreduce", 0, 0.0),
    ])
    def test_matrix(self, op, world, factor):
        assert comms.bus_factor(op, world) == pytest.approx(factor)

    def test_case_insensitive(self):
        # executor types constants are upper-case strings
        assert comms.bus_factor("ALLREDUCE", 4) == pytest.approx(1.5)

    def test_size_bucket_is_pow2_ceiling(self):
        assert comms.size_bucket(1) == 1
        assert comms.size_bucket(4096) == 4096
        assert comms.size_bucket(4097) == 8192
        assert comms.size_bucket(3 << 20) == 4 << 20
        assert comms.size_bucket(0) == 1  # degenerate, never crashes

    def test_fmt_bucket(self):
        assert comms._fmt_bucket(4 << 20) == "4MiB"
        assert comms._fmt_bucket(512) == "512B"
        assert comms._fmt_bucket(1 << 30) == "1GiB"


class TestRecording:
    def test_algbw_and_busbw_land_in_ledger(self, tracker):
        # 1 GB in 0.1 s at world=2: algbw 10 GB/s, allreduce factor 1.0
        tracker.record("allreduce", "device", 10 ** 9, 0.1, world=2)
        led = tracker.ledger()
        lane = led["lanes"]["device"]
        assert lane["busbw_gbs"] == pytest.approx(10.0, rel=1e-3)
        assert lane["bytes_total"] == 10 ** 9
        assert lane["ops_total"] == 1
        key = led["keys"][0]
        assert key["op"] == "allreduce"
        assert key["size_bucket"] == "1GiB"
        assert key["algbw_gbs"] == pytest.approx(10.0, rel=1e-3)
        assert key["busbw_gbs"] == pytest.approx(10.0, rel=1e-3)

    def test_per_record_world_beats_tracker_world(self, tracker):
        tracker.world = 1  # would zero busbw if used
        tracker.record("allreduce", "zero", 10 ** 9, 0.1, world=4)
        led = tracker.ledger()
        assert led["lanes"]["zero"]["busbw_gbs"] == pytest.approx(
            15.0, rel=1e-3)  # algbw 10 x 2(4-1)/4
        assert led["keys"][0]["busbw_gbs"] == pytest.approx(15.0, rel=1e-3)

    def test_world1_records_zero_busbw(self, tracker):
        tracker.record("allreduce", "device", 10 ** 9, 0.1, world=1)
        assert tracker.ledger()["lanes"]["device"]["busbw_gbs"] in (
            None, 0.0)

    def test_garbage_records_ignored(self, tracker):
        tracker.record("allreduce", "device", 0, 0.1)
        tracker.record("allreduce", "device", -5, 0.1)
        tracker.record("allreduce", "device", 100, 0.0)
        tracker.record("allreduce", "device", 100, -1.0)
        assert tracker.ledger()["lanes"] == {}

    def test_disabled_tracker_records_nothing(self, tracker):
        tracker.enabled = False
        tracker.record("allreduce", "device", 10 ** 9, 0.1)
        assert tracker.ledger()["lanes"] == {}

    def test_window_ring_is_bounded(self, tracker):
        tracker.window = 4
        for i in range(10):
            tracker.record("allreduce", "host_ring", 1 << 20, 0.001)
        with tracker._lock:
            (win,) = tracker._windows.values()
            assert len(win) == 4 and win.maxlen == 4
        assert tracker.ledger()["keys"][0]["ops"] == 4
        # totals keep the full history even as the window rolls
        assert tracker.ledger()["lanes"]["host_ring"]["ops_total"] == 10

    def test_sample_ring_is_bounded(self, tracker):
        for i in range(comms._SAMPLE_RING + 50):
            tracker.record("allreduce", "device", 1 << 20, 0.001)
        samples = tracker.samples()
        assert len(samples) == comms._SAMPLE_RING
        wall, busbw, lane = samples[-1]
        assert lane == "device" and busbw > 0


class TestRoofline:
    def test_probe_seed_beats_peak(self, tracker):
        tracker.seed_roofline("device", 50.0, source="probe")
        tracker.record("allreduce", "device", 10 ** 9, 0.1, world=2)
        lane = tracker.ledger()["lanes"]["device"]
        assert lane["roofline_gbs"] == pytest.approx(50.0)
        assert lane["roofline_source"] == "probe"
        assert lane["utilization"] == pytest.approx(0.2, rel=1e-3)

    def test_unseeded_lane_self_calibrates_from_peak(self, tracker):
        tracker.record("allreduce", "host_ring", 10 ** 9, 0.1, world=2)
        lane = tracker.ledger()["lanes"]["host_ring"]
        assert lane["roofline_source"] == "peak_observed"
        assert lane["roofline_gbs"] == pytest.approx(
            lane["peak_busbw_gbs"])
        assert lane["utilization"] == pytest.approx(1.0)

    def test_nonpositive_seed_ignored(self, tracker):
        tracker.seed_roofline("device", 0.0)
        tracker.seed_roofline("device", -3.0)
        with tracker._lock:
            assert "device" not in tracker._roofline


class TestDegradationDetector:
    def _fast(self, t, n=comms._WARMUP_OPS):
        for _ in range(n):
            t.record("allreduce", "host_ring", 10 ** 9, 0.1, world=2)

    def _slow(self, t, n=12):
        for _ in range(n):
            t.record("allreduce", "host_ring", 10 ** 7, 0.1, world=2)

    def test_alert_latches_once_and_rearms(self, tracker):
        before = len(_degraded_events("host_ring"))
        before_rec = len(_recovered_events("host_ring"))
        self._fast(tracker)
        assert not tracker.ledger()["lanes"]["host_ring"]["alerting"]
        # collapse busbw 100x: EWMA crosses below 0.5 of the peak
        self._slow(tracker)
        led = tracker.ledger()["lanes"]["host_ring"]
        assert led["alerting"] is True
        assert led["degraded_count"] == 1
        events = _degraded_events("host_ring")
        assert len(events) - before == 1  # ONE event while latched
        ev = events[-1]
        assert ev["op"] == "allreduce"
        assert ev["size_bucket"] == "16MiB"  # the bucket that slowed
        assert ev["utilization"] < ev["threshold"]
        assert ev["roofline_gbs"] > ev["busbw_gbs"]
        # recovery re-arms and emits comms_recovered
        self._fast(tracker, n=24)
        led = tracker.ledger()["lanes"]["host_ring"]
        assert led["alerting"] is False
        assert len(_recovered_events("host_ring")) - before_rec == 1
        # a SECOND sustained degradation fires a second event
        self._slow(tracker, n=24)
        assert len(_degraded_events("host_ring")) - before == 2
        assert tracker.ledger()["lanes"]["host_ring"][
            "degraded_count"] == 2

    def test_no_alert_during_warmup(self, tracker):
        tracker.seed_roofline("host_ring", 100.0)
        before = len(_degraded_events("host_ring"))
        # far below the roofline, but fewer records than _WARMUP_OPS
        self._slow(tracker, n=comms._WARMUP_OPS - 1)
        assert not tracker.ledger()["lanes"]["host_ring"]["alerting"]
        assert len(_degraded_events("host_ring")) == before

    def test_last_degraded_names_op_and_bucket(self, tracker):
        self._fast(tracker)
        self._slow(tracker)
        last = tracker.ledger()["lanes"]["host_ring"]["last_degraded"]
        assert last["op"] == "allreduce"
        assert last["size_bucket"] == "16MiB"
        assert last["utilization"] < 0.5


def _comms_state(rank, lanes):
    return {"rank": rank, "world": 2, "wall_time": 0.0,
            "degraded_fraction": 0.5, "lanes": lanes, "keys": []}


def _lane(busbw, roofline=None, alerting=False, last=None):
    util = (busbw / roofline) if roofline else None
    return {"busbw_gbs": busbw, "peak_busbw_gbs": busbw,
            "roofline_gbs": roofline,
            "roofline_source": "probe" if roofline else "none",
            "utilization": util, "bytes_total": 1 << 30, "ops_total": 10,
            "alerting": alerting, "degraded_count": int(alerting),
            "last_degraded": last}


def _dump(rank, comms_state):
    return {"schema": flight_recorder.SCHEMA, "rank": rank,
            "launch_rank": rank, "pid": 1000 + rank,
            "host": "host%d" % rank, "reason": "test", "wall_time": 0.0,
            "clock_offset_seconds": 0.0, "dump_history": [], "events": [],
            "state": {"comms": comms_state}, "metrics": {}}


class TestPostmortemReport:
    def test_cross_rank_report_names_slowest_lane_and_rank(self):
        dumps = [
            _dump(0, _comms_state(0, {
                "device": _lane(40.0, 50.0),
                "host_ring": _lane(2.0, 4.0)})),
            _dump(1, _comms_state(1, {
                "device": _lane(45.0, 50.0),
                "host_ring": _lane(
                    0.8, 4.0, alerting=True,
                    last={"wall_time": 0.0, "op": "allreduce",
                          "size_bucket": "16MiB", "busbw_gbs": 0.8,
                          "roofline_gbs": 4.0, "utilization": 0.2})})),
        ]
        text = comms.format_comms_report(dumps)
        assert "=== comms report (2 ranks) ===" in text
        assert "slowest lane: host_ring" in text
        assert "furthest below roofline: rank 1 host_ring" in text
        assert "DEGRADED" in text
        assert "degraded host_ring allreduce 16MiB" in text

    def test_report_empty_without_comms_state(self):
        dumps = [_dump(0, None)]
        dumps[0]["state"] = {}
        assert comms.format_comms_report(dumps) == ""

    def test_format_postmortem_embeds_comms_section(self):
        dumps = [_dump(0, _comms_state(0, {"device": _lane(40.0, 50.0)}))]
        text = flight_recorder.format_postmortem(dumps)
        assert "=== comms report" in text
        assert "device 40.00 GB/s/50.00 (80%)" in text


class TestConfigure:
    def test_knobs_and_provider_registration(self, singleton, monkeypatch):
        monkeypatch.setenv("HOROVOD_COMMS", "1")
        monkeypatch.setenv("HOROVOD_COMMS_WINDOW", "7")
        monkeypatch.setenv("HOROVOD_COMMS_EWMA_ALPHA", "0.5")
        monkeypatch.setenv("HOROVOD_COMMS_DEGRADED_FRACTION", "0.25")
        comms.configure(rank=3, world=4)
        assert singleton.enabled is True
        assert singleton.rank == 3 and singleton.world == 4
        assert singleton.window == 7
        assert singleton.ewma_alpha == 0.5
        assert singleton.degraded_fraction == 0.25
        assert "comms" in flight_recorder._recorder._providers
        monkeypatch.setenv("HOROVOD_COMMS", "0")
        comms.configure()
        assert singleton.enabled is False
        assert "comms" not in flight_recorder._recorder._providers

    def test_configure_seeds_rooflines_from_probe_cache(
            self, singleton, monkeypatch, tmp_path):
        from horovod_tpu.autotune import probe

        path = tmp_path / "roofline.json"
        path.write_text(json.dumps({
            "schema": probe._CACHE_SCHEMA, "hbm_gbps": 100.0,
            "allreduce_gbps": 30.0,
            "allreduce_busbw_gbps": 45.0, "world": 4,
            "fusion_threshold_bytes": 1 << 20, "wall_time": 0.0}))
        monkeypatch.setenv("HOROVOD_PROBE_CACHE", str(path))
        monkeypatch.setenv("HOROVOD_COMMS", "1")
        comms.configure(rank=0, world=4)
        with singleton._lock:
            assert singleton._roofline["device"] == pytest.approx(45.0)
            assert singleton._roofline["spmd"] == pytest.approx(45.0)
            assert singleton._roofline_source["device"] == "probe_cache"
        # host ring stays self-calibrating
        with singleton._lock:
            assert "host_ring" not in singleton._roofline

    def test_configure_seeds_hier_lane_rooflines(
            self, singleton, monkeypatch, tmp_path):
        """A schema-2 artifact with per-hop hierarchy numbers seeds the
        hier_intra/hier_cross lanes (separately — the two hops can
        differ by an order of magnitude)."""
        from horovod_tpu.autotune import probe

        path = tmp_path / "roofline.json"
        path.write_text(json.dumps({
            "schema": probe._CACHE_SCHEMA, "world": 4,
            "hier_intra_busbw_gbps": 12.0,
            "hier_cross_busbw_gbps": 0.75, "wall_time": 0.0}))
        monkeypatch.setenv("HOROVOD_PROBE_CACHE", str(path))
        monkeypatch.setenv("HOROVOD_COMMS", "1")
        with singleton._lock:
            # rooflines survive reset() by design; an earlier test (or a
            # runtime init elsewhere in the suite) may have seeded the
            # XLA lanes — start clean so the no-device assertion below
            # tests THIS artifact, not suite history
            singleton._roofline.clear()
            singleton._roofline_source.clear()
        comms.configure(rank=0, world=4)
        with singleton._lock:
            assert singleton._roofline["hier_intra"] == pytest.approx(12.0)
            assert singleton._roofline["hier_cross"] == pytest.approx(0.75)
            assert singleton._roofline_source["hier_cross"] == "probe_cache"
            # no mesh keys in this artifact: XLA lanes stay unseeded
            assert "device" not in singleton._roofline

    def test_comms_state_document(self, singleton):
        singleton.record("allreduce", "device", 1 << 20, 0.001, world=2)
        state = comms.comms_state()
        assert state["enabled"] is True
        assert "device" in state["lanes"]
        assert isinstance(state["samples"], list) and state["samples"]


class TestMetricsRoute:
    def test_get_comms_route(self, singleton):
        """The metrics server serves the ledger at GET /comms."""
        from horovod_tpu.metrics import MetricsRegistry

        singleton.record("allreduce", "device", 1 << 20, 0.001, world=2)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/comms" % port, timeout=5) as r:
                doc = json.loads(r.read().decode())
            assert doc["lanes"]["device"]["busbw_gbs"] > 0
            assert "keys" in doc and "samples" in doc
        finally:
            reg.stop_server()


class TestHvdTop:
    def _import_hvd_top(self):
        repo_tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools")
        if repo_tools not in sys.path:
            sys.path.insert(0, repo_tools)
        import hvd_top
        return hvd_top

    def test_comms_panel_against_live_endpoint(self, singleton):
        from horovod_tpu.metrics import MetricsRegistry

        hvd_top = self._import_hvd_top()
        singleton.seed_roofline("device", 20.0)
        singleton.record("allreduce", "device", 10 ** 9, 0.1, world=2)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            panel = hvd_top.render_comms(["127.0.0.1:%d" % port])
            assert "device" in panel.splitlines()[0]
            assert "10.00/20.00 (50%)" in panel
        finally:
            reg.stop_server()

    def test_comms_panel_empty_without_endpoint(self):
        hvd_top = self._import_hvd_top()
        assert hvd_top.render_comms(["127.0.0.1:1"]) == ""


class TestMergedTraceCounterTrack:
    def test_bus_bandwidth_counter_track(self, tmp_path):
        from horovod_tpu import profiler

        t0 = 1700000000.0
        dump = {"schema": "horovod-profiler-v1", "rank": 0,
                "launch_rank": 0, "clock_offset_seconds": 0.0,
                "steps": [], "trace_events": [
                    {"ph": "X", "pid": 0, "tid": 0, "ts": t0 * 1e6,
                     "dur": 1e4, "name": "step 0"}],
                "flight_events": [],
                "comms_samples": [[t0, 12.5, "device"],
                                  [t0 + 0.1, 3.25, "host_ring"],
                                  ["bogus", None, 3]]}
        with open(tmp_path / "profile-rank-0.json", "w") as f:
            json.dump(dump, f)
        out, n = profiler.merge_profile_dir(str(tmp_path))
        events = json.load(open(out))["traceEvents"]
        counters = [e for e in events
                    if e.get("name") == "bus bandwidth (GB/s)"]
        assert len(counters) == 2  # the malformed row was skipped
        assert all(e["ph"] == "C" for e in counters)
        assert counters[0]["args"] == {"device": 12.5}
        assert counters[1]["args"] == {"host_ring": 3.25}

    def test_profiler_snapshot_carries_comms_samples(self, singleton):
        from horovod_tpu import profiler

        singleton.record("allreduce", "device", 1 << 20, 0.001, world=2)
        snap = profiler._profiler.snapshot()
        assert snap["comms_samples"]
        assert snap["comms_samples"][-1][2] == "device"


class TestProbeCache:
    def _artifact(self, world=4):
        from horovod_tpu.autotune import probe

        return {"schema": probe._CACHE_SCHEMA, "hbm_gbps": 123.0,
                "allreduce_gbps": 30.0,
                "allreduce_busbw_gbps": 45.0, "world": world,
                "fusion_threshold_bytes": 1 << 20, "wall_time": 1.0}

    def test_roundtrip(self, tmp_path):
        from horovod_tpu.autotune import probe

        path = str(tmp_path / "sub" / "roofline.json")
        probe._persist_roofline(path, self._artifact())
        doc = probe.load_cached_roofline(path=path, world=4)
        assert doc["allreduce_busbw_gbps"] == 45.0
        # no stray tmp file survived the rename
        assert os.listdir(tmp_path / "sub") == ["roofline.json"]

    def test_world_mismatch_invalidates(self, tmp_path):
        from horovod_tpu.autotune import probe

        path = str(tmp_path / "roofline.json")
        probe._persist_roofline(path, self._artifact(world=4))
        assert probe.load_cached_roofline(path=path, world=8) is None
        assert probe.load_cached_roofline(path=path, world=4) is not None
        assert probe.load_cached_roofline(path=path) is not None  # unchecked

    def test_corrupt_schema_and_missing_are_none(self, tmp_path):
        from horovod_tpu.autotune import probe

        assert probe.load_cached_roofline(
            path=str(tmp_path / "nope.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{ torn")
        assert probe.load_cached_roofline(path=str(bad)) is None
        bad.write_text(json.dumps({"schema": 99, "world": 4}))
        assert probe.load_cached_roofline(path=str(bad)) is None
        assert probe.load_cached_roofline(path=None) is None  # knob unset

    def test_schema_1_artifact_invalidated(self, tmp_path):
        """Regression: a pre-hierarchy (schema 1) artifact must NOT
        reload under schema 2 — it knows nothing about the per-hop
        hierarchy split, so a 'cache hit' would leave the hier lanes
        unseeded while skipping the probes that would seed them."""
        from horovod_tpu.autotune import probe

        path = tmp_path / "roofline.json"
        path.write_text(json.dumps({
            "schema": 1, "hbm_gbps": 123.0, "allreduce_gbps": 30.0,
            "allreduce_busbw_gbps": 45.0, "world": 4,
            "fusion_threshold_bytes": 1 << 20, "wall_time": 1.0}))
        assert probe.load_cached_roofline(path=str(path), world=4) is None

    def test_probe_and_seed_reuses_cache(self, tmp_path, monkeypatch,
                                         singleton):
        """Second init with HOROVOD_PROBE_CACHE set must reload the
        artifact instead of re-probing (ISSUE 16 satellite)."""
        from horovod_tpu.autotune import probe

        calls = {"hbm": 0, "ar": 0}

        def fake_hbm(*a, **k):
            calls["hbm"] += 1
            return 100.0

        def fake_ar(mesh=None, **k):
            calls["ar"] += 1
            return {"algbw_gbps": 30.0, "busbw_gbps": 45.0, "world": 4}

        monkeypatch.setattr(probe, "probe_hbm_bandwidth", fake_hbm)
        monkeypatch.setattr(probe, "probe_allreduce_bandwidth", fake_ar)
        path = str(tmp_path / "roofline.json")
        monkeypatch.setenv("HOROVOD_PROBE_CACHE", path)
        mesh = types.SimpleNamespace(size=4)
        config = types.SimpleNamespace(cycle_time_ms=5.0,
                                       fusion_threshold_bytes=0)

        first = probe.probe_and_seed(config, mesh=mesh)
        assert first["cached"] is False
        assert calls == {"hbm": 1, "ar": 1}
        assert config.fusion_threshold_bytes > 0
        assert os.path.exists(path)

        config2 = types.SimpleNamespace(cycle_time_ms=5.0,
                                        fusion_threshold_bytes=0)
        second = probe.probe_and_seed(config2, mesh=mesh)
        assert second["cached"] is True
        assert calls == {"hbm": 1, "ar": 1}  # probes NOT re-run
        assert second["allreduce_busbw_gbps"] == 45.0
        assert (config2.fusion_threshold_bytes
                == config.fusion_threshold_bytes)
        # the measurement seeded the XLA-lane rooflines
        with singleton._lock:
            assert singleton._roofline["device"] == pytest.approx(45.0)

    def test_probe_and_seed_float_monkeypatch_compat(self, monkeypatch,
                                                     singleton):
        """Legacy tests monkeypatch probe_allreduce_bandwidth with a
        float-returning lambda; probe_and_seed must keep working."""
        from horovod_tpu.autotune import probe

        monkeypatch.setattr(probe, "probe_hbm_bandwidth",
                            lambda *a, **k: 100.0)
        monkeypatch.setattr(probe, "probe_allreduce_bandwidth",
                            lambda mesh=None, **k: 10.0)
        monkeypatch.delenv("HOROVOD_PROBE_CACHE", raising=False)
        mesh = types.SimpleNamespace(size=2)
        config = types.SimpleNamespace(cycle_time_ms=5.0,
                                       fusion_threshold_bytes=0)
        out = probe.probe_and_seed(config, mesh=mesh)
        assert out["allreduce_gbps"] == 10.0
        # factor 2(N-1)/N at N=2 is 1.0
        assert out["allreduce_busbw_gbps"] == pytest.approx(10.0)
        assert out["cached"] is False
