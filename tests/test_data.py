"""Data module tests: rank-sharded sampling + device prefetch.

The reference fixes the input convention in its examples
(DistributedSampler with num_replicas=hvd.size(), rank=hvd.rank();
reference: examples/pytorch_mnist.py) — ShardedSampler reproduces those
semantics framework-free, and the torch integration is pinned against
torch's own DistributedSampler.
"""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.data import ShardedSampler, prefetch_to_device

WORLD = 8


@pytest.fixture(autouse=True)
def _world():
    hvd.shutdown()
    hvd.init(mesh_shape=(1, WORLD))
    yield
    hvd.shutdown()


class TestShardedSampler:
    def test_disjoint_and_complete(self):
        n = 103  # not divisible by 8 — padding kicks in
        shards = [list(ShardedSampler(n, WORLD, r, seed=3))
                  for r in range(WORLD)]
        lengths = {len(s) for s in shards}
        assert lengths == {-(-n // WORLD)}  # equal ceil(n/world) everywhere
        seen = [i for s in shards for i in s]
        # padded by wrap-around: union covers the dataset exactly, with
        # total_size - n duplicates
        assert set(seen) == set(range(n))
        assert len(seen) == -(-n // WORLD) * WORLD

    def test_epoch_reshuffles_consistently(self):
        s0 = ShardedSampler(64, WORLD, 0, seed=1)
        s0b = ShardedSampler(64, WORLD, 0, seed=1)
        e0 = list(s0)
        s0.set_epoch(1)
        assert list(s0) != e0  # reshuffled
        s0b.set_epoch(1)
        assert list(s0) == list(s0b)  # deterministic across workers

    def test_no_shuffle_is_strided(self):
        s = ShardedSampler(16, 4, 1, shuffle=False)
        assert list(s) == [1, 5, 9, 13]

    def test_defaults_from_world(self):
        s = ShardedSampler(32)
        assert s.num_replicas == WORLD and s.rank == hvd.rank()

    def test_validation(self):
        with pytest.raises(ValueError):
            ShardedSampler(10, 4, 4)
        with pytest.raises(ValueError):
            ShardedSampler(0)

    def test_matches_torch_distributed_sampler_semantics(self):
        """Shard lengths/coverage equal torch's DistributedSampler with the
        reference's num_replicas/rank wiring (examples/pytorch_mnist.py)."""
        torch = pytest.importorskip("torch")
        from torch.utils.data.distributed import DistributedSampler

        n = 50
        dataset = list(range(n))
        for r in range(4):
            ts = DistributedSampler(dataset, num_replicas=4, rank=r,
                                    shuffle=True, seed=9)
            ts.set_epoch(2)
            ours = ShardedSampler(n, 4, r, seed=9)
            ours.set_epoch(2)
            t_idx, o_idx = list(ts), list(ours)
            assert len(t_idx) == len(o_idx)
            assert set(t_idx) <= set(range(n))
            assert set(o_idx) <= set(range(n))
        # both cover the dataset across ranks
        t_all = {i for r in range(4) for i in DistributedSampler(
            dataset, num_replicas=4, rank=r, shuffle=True, seed=9)}
        o_all = {i for r in range(4) for i in ShardedSampler(n, 4, r, seed=9)}
        assert t_all == o_all == set(range(n))


class TestPrefetch:
    def test_order_and_values(self):
        batches = [{"x": np.full((2,), i, np.float32)} for i in range(7)]
        out = list(prefetch_to_device(iter(batches), size=3))
        assert len(out) == 7
        for i, b in enumerate(out):
            import jax

            assert isinstance(b["x"], jax.Array)
            np.testing.assert_allclose(np.asarray(b["x"]), batches[i]["x"])

    def test_sharded_placement(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(hvd.mesh(), P(hvd.GLOBAL_AXES))
        batches = (np.arange(16, dtype=np.float32) + i for i in range(3))
        out = list(prefetch_to_device(batches, size=2, sharding=sharding))
        assert len(out) == 3
        assert out[0].sharding == sharding

    def test_source_error_propagates(self):
        def bad():
            yield np.zeros(2)
            raise RuntimeError("boom")

        it = prefetch_to_device(bad(), size=2)
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_early_close_stops_worker(self):
        import threading

        produced = []

        def src():
            for i in range(1000):
                produced.append(i)
                yield np.zeros(1)

        it = prefetch_to_device(src(), size=2)
        next(it)
        it.close()
        n_after = len(produced)
        import time

        time.sleep(0.1)
        # worker stopped: at most one more batch was in flight
        assert len(produced) <= n_after + 1
        assert threading.active_count() < 50

    def test_train_loop_end_to_end(self):
        """Sampler + prefetch feeding the global-batch train step."""
        import jax.numpy as jnp
        import optax

        from horovod_tpu import training
        from horovod_tpu.models.mnist import MnistConvNet

        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.sgd(0.05))
        state = training.create_train_state(model, opt, (1, 28, 28, 1))
        step, batch_sharding = training.make_train_step(model, opt)

        rng = np.random.RandomState(0)
        images = rng.rand(64, 28, 28, 1).astype(np.float32)
        labels = rng.randint(0, 10, 64).astype(np.int32)
        sampler = ShardedSampler(64, 1, 0, seed=0)  # global-batch: one view

        def batches():
            idx = list(sampler)
            for i in range(0, len(idx), 16):
                take = idx[i:i + 16]
                yield images[take], labels[take]

        p, s, o = state.params, state.batch_stats, state.opt_state
        losses = []
        for xb, yb in prefetch_to_device(batches(), size=2,
                                         sharding=batch_sharding):
            loss, p, s, o = step(p, s, o, xb, yb)
            losses.append(float(loss))
        assert len(losses) == 4
        assert np.isfinite(losses).all()
