"""Pipelined data-plane tests: size-bucketed program cache, identity
padding, persistent fusion buffers, and cycle pipelining.

The load-bearing guarantees: (1) padding a fused payload to its size
bucket never changes the reduced bits for any (reduce op, dtype) pair;
(2) steady-state cycles over the same named tensors hit the compiled
program cache even when bin-packing regroups them (zero new XLA compiles
after warmup — the acceptance criterion for the pipelined data plane);
(3) host staging slabs are reused, not reallocated, across cycles.
"""

import numpy as np
import pytest

import ml_dtypes

from horovod_tpu.runtime import fusion_buffer as fb
from horovod_tpu.runtime import message as msg, types
from horovod_tpu.runtime.fusion_buffer import (FusionBufferManager,
                                               bucket_elems, reduce_identity)


class TestBucketPolicy:
    def test_identity_below_quantum(self):
        # payloads at or under the quantum keep their exact size
        assert bucket_elems(10, 4, 64 * 1024) == 10
        assert bucket_elems(16384, 4, 64 * 1024) == 16384  # exactly 64 KiB

    def test_power_of_two_above_quantum(self):
        q = 64 * 1024
        assert bucket_elems(16385, 4, q) == (2 * q) // 4
        assert bucket_elems(40000, 4, q) == (4 * q) // 4  # 160000B -> 256KiB

    def test_distinct_sizes_share_a_bucket(self):
        # the collapse that makes regrouped bins reuse one program
        assert bucket_elems(300, 4, 256) == bucket_elems(400, 4, 256) == 512

    def test_quantum_zero_disables_bucketing(self):
        assert bucket_elems(12345, 4, 0) == 12345

    def test_ceil_when_itemsize_does_not_divide(self):
        # 3 * 100 = 300B > 256 -> 512B bucket -> ceil(512/3) = 171 elems
        assert bucket_elems(100, 3, 256) == 171

    def test_reduce_identities(self):
        assert reduce_identity(np.float32, types.REDUCE_SUM) == 0.0
        assert reduce_identity(np.int32, types.REDUCE_AVERAGE) == 0
        assert reduce_identity(np.float32, types.REDUCE_PRODUCT) == 1.0
        assert reduce_identity(np.float32, types.REDUCE_MIN) == np.inf
        assert reduce_identity(np.float32, types.REDUCE_MAX) == -np.inf
        assert (reduce_identity(np.int32, types.REDUCE_MIN)
                == np.iinfo(np.int32).max)
        assert (reduce_identity(np.int32, types.REDUCE_MAX)
                == np.iinfo(np.int32).min)
        bf16 = np.dtype(ml_dtypes.bfloat16)
        assert reduce_identity(bf16, types.REDUCE_MIN) == np.inf
        assert reduce_identity(bf16, types.REDUCE_SUM) == 0
        with pytest.raises(ValueError):
            reduce_identity(np.float32, "median")

    def test_identity_keeps_dtype(self):
        for dt in (np.float32, np.int32, np.dtype(ml_dtypes.bfloat16)):
            for op in types.REDUCE_OPS:
                assert np.asarray(reduce_identity(dt, op)).dtype == dt


class TestFusionBufferManager:
    def test_reuse_after_release(self):
        mgr = FusionBufferManager(256)
        allocs0 = fb._BUF_ALLOCS.value
        lease = mgr.acquire(2, 300, np.float32)
        assert lease.array.shape == (2, 512)  # 1200B -> 2048B bucket
        assert mgr.live_bytes() == lease.array.nbytes
        assert mgr.leases_outstanding() == 1
        mgr.release(lease)
        assert mgr.live_bytes() == 0
        assert mgr.leases_outstanding() == 0
        again = mgr.acquire(2, 400, np.float32)  # same bucket, reused
        assert again.array is lease.array
        assert fb._BUF_ALLOCS.value - allocs0 == 1
        assert mgr.live_bytes() == again.array.nbytes
        mgr.release(again)
        assert mgr.live_bytes() == 0

    def test_outstanding_leases_get_distinct_slabs(self):
        mgr = FusionBufferManager(256)
        a = mgr.acquire(1, 100, np.float32)
        b = mgr.acquire(1, 100, np.float32)  # a still leased (pipelining)
        assert a.array is not b.array
        assert mgr.leases_outstanding() == 2
        assert mgr.live_bytes() == a.array.nbytes + b.array.nbytes
        mgr.release(a)
        mgr.release(b)
        assert mgr.leases_outstanding() == 0
        assert mgr.live_bytes() == 0

    def test_allocated_bytes_tracks_slabs(self):
        mgr = FusionBufferManager(0)  # identity buckets
        lease = mgr.acquire(4, 10, np.float32)
        assert mgr.allocated_bytes() == 4 * 10 * 4
        mgr.release(lease)
        reuse = mgr.acquire(4, 10, np.float32)
        assert mgr.allocated_bytes() == 4 * 10 * 4  # no second slab
        mgr.release(reuse)

    def test_release_is_idempotent(self):
        # the memory plane's live-bytes gauge must not go negative when a
        # failure path and a finally block both release the same lease
        mgr = FusionBufferManager(256)
        lease = mgr.acquire(1, 100, np.float32)
        mgr.release(lease)
        mgr.release(lease)  # no-op, not a double decrement
        assert mgr.live_bytes() == 0
        assert mgr.leases_outstanding() == 0

    def test_bytes_by_purpose_ledger(self):
        mgr = FusionBufferManager(256, purpose="fusion")
        stage = FusionBufferManager(256, purpose="ckpt_staging")
        lease = mgr.acquire(1, 100, np.float32)
        ledger = fb.bytes_by_purpose()
        assert ledger["fusion"]["live_bytes"] >= lease.array.nbytes
        assert ledger["fusion"]["leases_outstanding"] >= 1
        assert "ckpt_staging" in ledger
        assert ledger["ckpt_staging"]["live_bytes"] == 0
        mgr.release(lease)
        assert stage.live_bytes() == 0


_AB_CASES = [(op, dt)
             for op in (types.REDUCE_SUM, types.REDUCE_AVERAGE,
                        types.REDUCE_MIN, types.REDUCE_MAX,
                        types.REDUCE_PRODUCT)
             for dt in ("float32", "bfloat16", "int32")]


class TestPaddingCorrectness:
    """Padded fused allreduce must bit-match the unpadded result for every
    (reduce op, dtype) pair — the pad columns carry the reduction identity
    and are sliced off before unpack."""

    def _run_fused(self, hvd, executor, op, dtype, quantum, tag):
        rng = np.random.RandomState(7)
        dt = np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16" \
            else np.dtype(dtype)
        entries = []
        for j, n in enumerate((5, 3, 9)):  # odd sizes -> real padding
            if dt.kind == "i":
                vals = [rng.randint(-50, 50, size=(n,)).astype(dt)
                        for _ in range(hvd.size())]
            else:
                vals = [(rng.randn(n) * 3).astype(dt)
                        for _ in range(hvd.size())]
            entries.append(types.TensorTableEntry(
                name=f"pad/{tag}/{op}/{dtype}/t{j}",
                tensor=hvd.stack_per_worker(vals), reduce_op=op))
        saved = executor.fusion_buffers
        executor.fusion_buffers = FusionBufferManager(quantum)
        try:
            executor.execute(
                msg.Response(types.ALLREDUCE, [e.name for e in entries]),
                entries)
        finally:
            executor.fusion_buffers = saved
        for e in entries:
            assert e.output is not None, f"{e.name} did not complete"
        return [np.asarray(e.output) for e in entries]

    @pytest.mark.parametrize("op,dtype", _AB_CASES)
    def test_padded_bitmatches_unpadded(self, hvd, op, dtype):
        from horovod_tpu.runtime.runtime import get_runtime

        ex = get_runtime().executor
        # quantum 16B: every payload rounds up to a power of two (padded);
        # quantum 1<<30: identity bucketing (never padded)
        padded = self._run_fused(hvd, ex, op, dtype, 16, "q16")
        exact = self._run_fused(hvd, ex, op, dtype, 1 << 30, "exact")
        for a, b in zip(padded, exact):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)


class TestSteadyStateProgramCache:
    """Acceptance criterion: same named tensors every cycle, varying fused
    bins -> zero new XLA compiles after warmup, observed through
    horovod_executor_program_compiles_total."""

    def _one_cycle(self, hvd, rt, threshold_bytes, step):
        """Enqueue 4 named tensors inside one held cycle, then release the
        loop with ``fusion_threshold_bytes`` set so bin-packing groups
        them as the threshold dictates."""
        from horovod_tpu.core import state

        st = state.global_state()
        saved_thresh = st.config.fusion_threshold_bytes
        real_cycle = rt.run_cycle
        rt.run_cycle = lambda: True  # hold: queue everything first
        try:
            st.config.fusion_threshold_bytes = threshold_bytes
            handles = [
                hvd.allreduce_async(
                    hvd.stack_per_worker(
                        [np.full((300,), float(i + j + step), "float32")
                         for i in range(hvd.size())]),
                    name=f"steady/t{j}")
                for j in range(4)]
        finally:
            rt.run_cycle = real_cycle
            rt._woken.set()
        outs = [np.asarray(hvd.synchronize(h)) for h in handles]
        st.config.fusion_threshold_bytes = saved_thresh
        for j, out in enumerate(outs):
            expected = np.mean([i + j + step for i in range(hvd.size())])
            np.testing.assert_allclose(out, np.full((300,), expected),
                                       rtol=1e-6)

    def test_varying_bins_zero_compiles_after_warmup(self, hvd, monkeypatch):
        from horovod_tpu.runtime import executor as ex_mod
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        # small quantum so the 4x(8,300) float32 tensors exercise real
        # power-of-two buckets: a 3-tensor bin (3600B/row) and a 2-tensor
        # bin (2400B/row) both land in the 4096B bucket
        monkeypatch.setattr(rt.executor, "fusion_buffers",
                            FusionBufferManager(256))
        # warmup: one grouping {t0,t1,t2},{t3} compiles the 4096B and
        # 2048B buckets (per-tensor request is 8*300*4 = 9600B)
        self._one_cycle(hvd, rt, threshold_bytes=30000, step=0)
        compiles_after_warmup = ex_mod._PROGRAM_COMPILES.value
        hits0 = ex_mod._PROGRAM_CACHE_HITS.value
        allocs0 = fb._BUF_ALLOCS.value
        # steady state: regrouped bins {t0,t1},{t2,t3} (never seen before)
        # plus the warmup grouping again — all hit the warmed buckets
        for step in range(1, 4):
            self._one_cycle(hvd, rt, threshold_bytes=20000, step=step)
        self._one_cycle(hvd, rt, threshold_bytes=30000, step=4)
        assert ex_mod._PROGRAM_COMPILES.value == compiles_after_warmup, \
            "steady-state cycles must not trigger new XLA compiles"
        assert ex_mod._PROGRAM_CACHE_HITS.value > hits0
        # the single-controller path packs on device: sharded gradients
        # never stage through (or allocate) host fusion-buffer slabs
        assert fb._BUF_ALLOCS.value == allocs0, \
            "device-path cycles must not allocate host staging slabs"

    @pytest.mark.parametrize("depth", [1, 3])
    def test_pipeline_depth_preserves_results(self, hvd, monkeypatch, depth):
        from horovod_tpu.core import state
        from horovod_tpu.runtime import runtime as rt_mod
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        monkeypatch.setattr(state.global_state().config,
                            "cycle_pipeline_depth", depth)
        # multi-bin cycle (threshold fits 2 of the 9600B requests)
        self._one_cycle(hvd, rt, threshold_bytes=20000, step=10 + depth)
        assert rt_mod._PIPELINE_DEPTH.value == 0  # drained


class TestDeviceResidency:
    """The single-controller fused allreduce must stay on device end to
    end: inputs are sharded jax.Arrays and outputs come back as replicated
    jax.Arrays — never host numpy round-trips on the hot path."""

    def test_outputs_are_replicated_jax_arrays(self, hvd):
        import jax

        from horovod_tpu.runtime.runtime import get_runtime

        ex = get_runtime().executor
        entries = [types.TensorTableEntry(
            name=f"resid/t{j}",
            tensor=hvd.stack_per_worker(
                [np.full((7,), float(i + j), "float32")
                 for i in range(hvd.size())]),
            reduce_op=types.REDUCE_SUM) for j in range(3)]
        saved = ex.fusion_buffers
        ex.fusion_buffers = FusionBufferManager(16)  # force real padding
        try:
            allocs0 = fb._BUF_ALLOCS.value
            ex.execute(msg.Response(types.ALLREDUCE,
                                    [e.name for e in entries]), entries)
        finally:
            ex.fusion_buffers = saved
        assert fb._BUF_ALLOCS.value == allocs0  # no host staging slabs
        for j, e in enumerate(entries):
            assert isinstance(e.output, jax.Array), \
                "single-controller allreduce must return device arrays"
            assert e.output.sharding.is_fully_replicated
            np.testing.assert_allclose(
                np.asarray(e.output),
                np.full((7,), sum(i + j for i in range(hvd.size())),
                        "float32"))


class _FailingNet:
    """Ring stub whose allreduce always loses the transport."""

    world = 2
    rank = 0

    def allreduce(self, buf, op):
        raise RuntimeError("ring transport lost")


class TestLeaseLifecycle:
    """Fusion-buffer leases must come back on every failure path —
    transient faults (routine under elastic) must not grow host memory."""

    def _slabs_free(self, mgr):
        return sum(len(v) for v in mgr._free.values())

    def test_host_ring_failure_releases_lease(self, hvd):
        from horovod_tpu.core import state
        from horovod_tpu.runtime.executor import Executor

        ex = Executor(state.global_state().mesh, net=_FailingNet())
        ex.fusion_buffers = FusionBufferManager(256)
        entries = [types.TensorTableEntry(
            name="leak/ring", tensor=np.ones((10,), "float32"),
            reduce_op=types.REDUCE_SUM)]
        with pytest.raises(RuntimeError):
            ex._execute_allreduce_host(entries)
        assert self._slabs_free(ex.fusion_buffers) == 1, \
            "slab must return to the free list when the ring raises"
        assert ex.fusion_buffers.live_bytes() == 0, \
            "live-bytes gauge must drop back to baseline on failure"
        assert ex.fusion_buffers.leases_outstanding() == 0

    def test_token_fail_releases_lease(self, hvd):
        from horovod_tpu.core import state
        from horovod_tpu.runtime import executor as ex_mod

        ex = ex_mod.Executor(state.global_state().mesh)
        ex.fusion_buffers = FusionBufferManager(256)
        lease = ex.fusion_buffers.acquire(1, 100, np.float32)
        entry = types.TensorTableEntry(name="leak/tok",
                                       tensor=np.ones((4,), "float32"))
        tok = ex_mod._PendingOp(ex, types.ALLREDUCE, [entry], None)
        tok.lease = lease
        tok.fail(types.Status.UnknownError("cycle aborted"))
        assert tok.lease is None
        assert self._slabs_free(ex.fusion_buffers) == 1, \
            "failing a pending token must release its slab lease"
        assert ex.fusion_buffers.live_bytes() == 0
        # idempotent: a second fail must not double-release
        tok.fail(types.Status.UnknownError("again"))
        assert self._slabs_free(ex.fusion_buffers) == 1
        assert ex.fusion_buffers.live_bytes() == 0
        assert ex.fusion_buffers.leases_outstanding() == 0


class TestKnobParsing:
    def test_defaults(self, monkeypatch):
        from horovod_tpu.utils import env

        monkeypatch.delenv(env.HOROVOD_CYCLE_PIPELINE_DEPTH, raising=False)
        monkeypatch.delenv(env.HOROVOD_FUSION_BUCKET_QUANTUM, raising=False)
        cfg = env.Config.from_env()
        assert cfg.cycle_pipeline_depth == 2
        assert cfg.fusion_bucket_quantum == 64 * 1024

    def test_overrides(self, monkeypatch):
        from horovod_tpu.utils import env

        monkeypatch.setenv(env.HOROVOD_CYCLE_PIPELINE_DEPTH, "4")
        monkeypatch.setenv(env.HOROVOD_FUSION_BUCKET_QUANTUM, "1024")
        cfg = env.Config.from_env()
        assert cfg.cycle_pipeline_depth == 4
        assert cfg.fusion_bucket_quantum == 1024
