"""Unit tests for the elastic subsystem (PR 2 satellites).

Process-local pieces: backoff arithmetic, fault-spec parsing, the
exception hierarchy, state commit/restore round-trips and spill,
rendezvous long-poll / TTL / key listing, checkpoint write hardening,
driver membership math, and the stall inspector's elastic raise. The
end-to-end kill/re-form path lives in test_elastic_multiprocess.py.
"""

import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import checkpoint, exceptions
from horovod_tpu.elastic import (ArrayState, Backoff, FaultSpec, ObjectState,
                                 fault_inject)
from horovod_tpu.elastic.driver import ElasticDriver, HostDiscoveryScript
from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer
from horovod_tpu.stall import StallInspector


# ---------------------------------------------------------------------------
# exceptions
# ---------------------------------------------------------------------------

class TestExceptionHierarchy:
    def test_workers_down_is_runtime_error(self):
        # back-compat: pre-elastic callers catch RuntimeError
        assert issubclass(exceptions.WorkersDownError, RuntimeError)
        assert issubclass(exceptions.WorkerLostError,
                          exceptions.WorkersDownError)
        assert issubclass(exceptions.WorkerStallError,
                          exceptions.WorkersDownError)

    def test_hosts_updated_is_not_a_failure(self):
        # the interrupt must NOT be caught by `except RuntimeError`
        assert not issubclass(exceptions.HostsUpdatedInterrupt, RuntimeError)

    def test_ranks_carried(self):
        e = exceptions.WorkerLostError("gone", ranks=[2, 1])
        assert e.ranks == (2, 1)
        assert exceptions.WorkersDownError("x").ranks == ()

    def test_exported_at_package_root(self):
        assert hvd.WorkersDownError is exceptions.WorkersDownError
        assert hvd.HostsUpdatedInterrupt is exceptions.HostsUpdatedInterrupt


# ---------------------------------------------------------------------------
# backoff
# ---------------------------------------------------------------------------

class TestBackoff:
    def test_schedule_doubles_and_caps(self):
        b = Backoff(base=0.5, factor=2.0, max_delay=3.0, retries=5)
        assert b.schedule() == [0.5, 1.0, 2.0, 3.0, 3.0]

    def test_zero_retries_empty(self):
        assert Backoff(retries=0).schedule() == []

    def test_invalid_params_raise(self):
        with pytest.raises(ValueError):
            Backoff(base=0)
        with pytest.raises(ValueError):
            Backoff(factor=0.5)
        with pytest.raises(ValueError):
            Backoff(retries=-1)

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_ELASTIC_BACKOFF_BASE_SECONDS", "1.0")
        monkeypatch.setenv("HOROVOD_ELASTIC_BACKOFF_MAX_SECONDS", "4.0")
        monkeypatch.setenv("HOROVOD_ELASTIC_MAX_RETRIES", "3")
        assert Backoff.from_env().schedule() == [1.0, 2.0, 4.0]


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

class TestFaultInject:
    def test_parse_kill(self):
        spec = fault_inject.parse_spec("kill:rank=1:step=3:code=17")
        assert spec == FaultSpec(action="kill", rank=1, step=3, code=17,
                                 seconds=spec.seconds, generation=0)

    def test_parse_hang_with_gen(self):
        spec = fault_inject.parse_spec("hang:rank=0:step=2:seconds=5:gen=1")
        assert (spec.action, spec.seconds, spec.generation) == ("hang", 5.0, 1)

    @pytest.mark.parametrize("bad", [
        "explode:rank=0:step=1",   # unknown action
        "kill:rank=0",             # missing step
        "kill:step=1",             # missing rank
        "kill:rank=x:step=1",      # non-integer
        "",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            fault_inject.parse_spec(bad)

    def test_maybe_inject_ignores_other_rank_and_step(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FAULT_INJECT", "kill:rank=7:step=3")
        # wrong rank: nothing happens (we are obviously still alive after)
        fault_inject.maybe_inject(step=3, rank=0)
        # right rank, wrong step
        fault_inject.maybe_inject(step=2, rank=7)
        # right rank+step, wrong generation
        monkeypatch.setenv("HOROVOD_FAULT_INJECT", "kill:rank=0:step=3:gen=2")
        fault_inject.maybe_inject(step=3, rank=0, generation=0)

    def test_multiple_process_clauses_all_armed(self, monkeypatch):
        # a multi-rank chaos cell arms one kill per target rank; the
        # worker whose rank is named only by the SECOND clause must
        # still see it (spec_from_env's first-clause view used to drop
        # every later process fault on the floor)
        monkeypatch.setenv(
            "HOROVOD_FAULT_INJECT",
            "netdelay:5:hop=cross;"
            "kill:rank=4:step=3:code=17;kill:rank=5:step=5:code=19:gen=1")
        specs = fault_inject.specs_from_env()
        assert [(s.rank, s.step, s.code, s.generation) for s in specs] \
            == [(4, 3, 17, 0), (5, 5, 19, 1)]
        assert fault_inject.spec_from_env() == specs[0]
        # rank 5 consults both clauses but matches neither here
        # (wrong step / wrong generation) — still alive proves no fire
        fault_inject.maybe_inject(step=5, rank=5, generation=0)
        fault_inject.maybe_inject(step=4, rank=5, generation=1)


# ---------------------------------------------------------------------------
# state commit / restore
# ---------------------------------------------------------------------------

class TestObjectState:
    def test_commit_restore_round_trip(self):
        state = ObjectState(batch=0, epoch=0, table={"a": 1})
        state.batch = 5
        state.table["a"] = 2
        state.commit()
        state.batch = 9
        state.table["a"] = 99
        state.restore()
        assert state.batch == 5
        assert state.table == {"a": 2}

    def test_snapshot_is_by_value(self):
        # mutating a live attr must not leak into the committed snapshot
        state = ObjectState(history=[1, 2])
        state.commit()
        state.history.append(3)
        state.restore()
        assert state.history == [1, 2]

    def test_reset_callbacks_fire_on_reset(self):
        calls = []
        state = ObjectState(x=1)
        state.register_reset_callbacks([lambda: calls.append("a"),
                                        lambda: calls.append("b")])
        state.on_reset()
        assert calls == ["a", "b"]


class TestArrayState:
    def test_commit_restore_round_trip(self):
        state = ArrayState(params={"w": np.zeros(3, np.float32)},
                           optimizer={"m": np.ones(3, np.float32)}, step=0)
        state.params["w"] = state.params["w"] + 2
        state.step = 4
        state.commit()
        state.params["w"] = state.params["w"] * 50
        state.optimizer["m"] = state.optimizer["m"] * 50
        state.step = 7
        state.restore()
        assert state.step == 4
        np.testing.assert_array_equal(state.params["w"], [2, 2, 2])
        np.testing.assert_array_equal(state.optimizer["m"], [1, 1, 1])

    def test_initial_values_snapshot_at_construction(self):
        state = ArrayState(params={"w": np.arange(3)}, optimizer=None)
        state.params["w"] = np.full(3, -1)
        state.restore()  # failure before the first commit -> starting point
        np.testing.assert_array_equal(state.params["w"], [0, 1, 2])

    def test_extra_trees(self):
        state = ArrayState(params=None, optimizer=None,
                           ema={"w": np.ones(2)})
        state.ema["w"] = state.ema["w"] * 3
        state.commit()
        state.ema["w"] = state.ema["w"] * 100
        state.restore()
        np.testing.assert_array_equal(state.ema["w"], [3, 3])

    def test_sync_single_process_no_op(self):
        hvd.init()
        try:
            state = ArrayState(params={"w": np.ones(2)}, optimizer=None,
                               step=3)
            state.sync(root_rank=0)
            assert state.step == 3
        finally:
            hvd.shutdown()

    def test_sync_spill_writes_checkpoint(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_ELASTIC_SPILL_SYNC", "1")
        hvd.init()
        try:
            state = ArrayState(params={"w": np.ones(2, np.float32)},
                               optimizer=None, step=0,
                               spill_dir=str(tmp_path))
            state.step = 2
            state.commit()
            assert checkpoint.latest_step(str(tmp_path)) == 2
        finally:
            hvd.shutdown()


# ---------------------------------------------------------------------------
# rendezvous: long-poll, TTL, key listing
# ---------------------------------------------------------------------------

@pytest.fixture
def rendezvous():
    server = RendezvousServer(host="127.0.0.1", heartbeat_ttl=0.3)
    port = server.start()
    yield server, port
    server.stop()


class TestRendezvous:
    def test_long_poll_wakes_on_put(self, rendezvous):
        server, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="s", timeout=10,
                               long_poll=5.0)
        threading.Timer(0.3, client.set, args=("k", b"v")).start()
        t0 = time.monotonic()
        assert client.get("k") == b"v"
        # woken by the PUT's notify, far before the 5s poll window closes
        assert time.monotonic() - t0 < 3.0

    def test_get_nowait_raises_keyerror(self, rendezvous):
        _, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="s", timeout=1)
        with pytest.raises(KeyError):
            client.get("missing", wait=False)

    def test_keys_listing(self, rendezvous):
        _, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="m", timeout=1)
        client.set("member.0", b"a")
        client.set("member.2", b"b")
        assert client.keys("m") == ["member.0", "member.2"]
        assert client.keys("empty-scope") == []

    def test_heartbeat_ttl_expires(self, rendezvous):
        server, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="heartbeat",
                               timeout=1)
        client.set("w0", b"beat")
        assert server.live_keys("heartbeat") == ["w0"]
        time.sleep(0.4)  # past the 0.3s TTL
        assert server.live_keys("heartbeat") == []
        # an expired heartbeat also reads as absent
        with pytest.raises(KeyError):
            client.get("w0", wait=False)

    def test_ttl_param_filters_listing(self, rendezvous):
        _, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="g", timeout=1)
        client.set("old", b"x")
        time.sleep(0.2)
        client.set("new", b"y")
        assert client.keys("g", ttl=0.1) == ["new"]
        assert client.keys("g") == ["new", "old"]

    def test_server_side_put(self, rendezvous):
        server, port = rendezvous
        server.put("elastic.notice", "update", b"notice-1")
        client = KVStoreClient("127.0.0.1", port, scope="elastic.notice",
                               timeout=1)
        assert client.get("update", wait=False) == b"notice-1"


# ---------------------------------------------------------------------------
# checkpoint hardening
# ---------------------------------------------------------------------------

class TestCheckpointHardening:
    def test_stale_tmp_cleaned_fresh_kept(self, tmp_path):
        hvd.init()
        try:
            stale = tmp_path / "dead-writer.tmp"
            stale.write_bytes(b"torn")
            old = time.time() - 3600
            os.utime(stale, (old, old))
            fresh = tmp_path / "live-writer.tmp"
            fresh.write_bytes(b"in-flight")

            checkpoint.save(str(tmp_path), {"w": np.ones(2)}, step=1)

            assert not stale.exists()
            assert fresh.exists()
            assert checkpoint.latest_step(str(tmp_path)) == 1
        finally:
            hvd.shutdown()

    def test_save_remains_atomic(self, tmp_path):
        hvd.init()
        try:
            path = checkpoint.save(str(tmp_path), {"w": np.arange(4)}, step=7)
            assert os.path.basename(path) == "ckpt_7.msgpack"
            # no droppings
            assert [n for n in os.listdir(tmp_path)
                    if n.endswith(".tmp")] == []
        finally:
            hvd.shutdown()


# ---------------------------------------------------------------------------
# driver membership math + notices
# ---------------------------------------------------------------------------

class TestElasticDriver:
    def test_diff_hosts(self):
        added, removed = ElasticDriver.diff_hosts(
            {"a": 2, "b": 2}, {"a": 2, "c": 4})
        assert added == ["c"]
        assert removed == ["b"]

    def test_diff_hosts_slot_change_is_both(self):
        added, removed = ElasticDriver.diff_hosts({"a": 2}, {"a": 4})
        assert added == ["a"]
        assert removed == ["a"]

    def test_discovery_script_parsing(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\n"
                          "echo host1:4\n"
                          "echo '# comment'\n"
                          "echo host2\n")
        script.chmod(0o755)
        hosts = HostDiscoveryScript(str(script)).find_available_hosts()
        assert hosts == {"host1": 4, "host2": 1}

    def test_host_change_publishes_notice(self, rendezvous):
        server, _ = rendezvous
        snapshots = iter([{"a": 1, "b": 1}, {"a": 1}])
        discovery = SimpleNamespace(
            find_available_hosts=lambda: next(snapshots))
        driver = ElasticDriver(server, discovery, heartbeat_ttl=60)
        driver._hosts = discovery.find_available_hosts()  # baseline
        driver._poll_once()  # sees host b removed
        notice = json.loads(server.get("elastic.notice", "update").decode())
        assert "b" in notice["notice"]
        assert notice["seq"] == 1

    def test_heartbeat_loss_detected(self, rendezvous):
        server, port = rendezvous
        client = KVStoreClient("127.0.0.1", port, scope="heartbeat",
                               timeout=1)
        driver = ElasticDriver(server, discovery=None, heartbeat_ttl=0.2)
        client.set("w0", b"beat")
        assert driver._check_heartbeats() == set()      # first seen: live
        time.sleep(0.3)                                 # beat goes stale
        assert driver._check_heartbeats() == {"w0"}


# ---------------------------------------------------------------------------
# stall inspector: elastic raise
# ---------------------------------------------------------------------------

def _stalled_table(age: float, world: int = 2):
    now = time.monotonic()
    return SimpleNamespace(
        pending=lambda: {"t": [SimpleNamespace(rank=0)]},
        first_request_time=lambda name: now - age)


class TestStallElastic:
    def test_elastic_stall_raises_typed(self):
        inspector = StallInspector(warning_time_seconds=0.0,
                                   shutdown_time_seconds=1.0, elastic=True)
        inspector._last_check = time.monotonic() - 1
        with pytest.raises(exceptions.WorkerStallError) as exc_info:
            inspector.check(_stalled_table(age=10.0), world=2)
        assert exc_info.value.ranks == (1,)

    def test_non_elastic_stall_returns_true(self):
        inspector = StallInspector(warning_time_seconds=0.0,
                                   shutdown_time_seconds=1.0, elastic=False)
        inspector._last_check = time.monotonic() - 1
        assert inspector.check(_stalled_table(age=10.0), world=2) is True


# ---------------------------------------------------------------------------
# metrics + config knobs
# ---------------------------------------------------------------------------

class TestElasticMetrics:
    def test_elastic_families_registered(self):
        names = {f["name"] if isinstance(f, dict) else f
                 for f in hvd.metrics()}
        for metric in ("horovod_elastic_commits_total",
                       "horovod_elastic_commit_duration_seconds",
                       "horovod_elastic_restarts_total",
                       "horovod_elastic_workers_removed_total",
                       "horovod_elastic_generation",
                       "horovod_elastic_faults_injected_total"):
            assert metric in names, (metric, sorted(names))

    def test_commit_moves_counters(self):
        def commits():
            values = hvd.metrics()["horovod_elastic_commits_total"]["values"]
            return values[0]["value"] if values else 0

        before = commits()
        ObjectState(x=1).commit()
        assert commits() == before + 1


class TestConfigKnob:
    def test_elastic_config_parsing(self, monkeypatch):
        from horovod_tpu.utils.env import Config

        assert Config.from_env().elastic is False
        monkeypatch.setenv("HOROVOD_ELASTIC", "1")
        assert Config.from_env().elastic is True
