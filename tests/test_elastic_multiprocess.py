"""Elastic fault-injection acceptance test (ISSUE.md PR 2).

World=3 over the real socket/native transport; the pytest process hosts
the rendezvous HTTP KV store (standing in for the tpurun launcher).
``HOROVOD_FAULT_INJECT=kill:rank=1:step=3`` hard-kills rank 1 inside its
step-3 commit; ranks 0 and 2 must catch WorkersDownError, re-form into a
2-worker generation through the store, roll back to the last commit and
finish all 8 steps with the training invariant (w == step) intact.
"""

import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.runtime.native import native_built

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "elastic_worker.py")
ZERO_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "zero_elastic_worker.py")
BUCKET_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bucket_elastic_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_elastic(world: int, extra_env=None, timeout=240,
                    worker=WORKER):
    rendezvous = RendezvousServer(host="127.0.0.1")
    http_port = rendezvous.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                # survivors must notice the dead peer quickly, not after
                # the default 30s verb timeout
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "JAX_PLATFORMS": "cpu",
            })
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, worker],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        rendezvous.stop()
    return procs, outs


def test_kill_rank1_at_step3_survivors_finish():
    """The ISSUE.md acceptance scenario: rank 1 killed at step 3 of an
    8-step run; ranks 0 and 2 restore from the last commit and complete
    all 8 steps in a 2-worker generation."""
    procs, outs = _launch_elastic(
        3, extra_env={
            "HOROVOD_FAULT_INJECT": "kill:rank=1:step=3:code=17",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        })
    # the planted death exits with the injected code
    assert procs[1].returncode == 17, outs[1]
    for i in (0, 2):
        assert procs[i].returncode == 0, (i, outs[i])
        assert "DONE" in outs[i], (i, outs[i])
        assert "step=8" in outs[i], (i, outs[i])
        assert "w=8" in outs[i], (i, outs[i])
        assert "size=2" in outs[i], (i, outs[i])
        # metrics satellite: the restart was counted
        restarts = float(outs[i].split(
            "elastic_restarts_total=")[1].split()[0])
        assert restarts >= 1, (i, outs[i])


def test_zero_sharded_state_survives_reform():
    """ZeRO-1 acceptance (ISSUE.md PR 5): the SHARDED optimizer state
    must survive rank 1 dying at step 3 — ``ArrayState.sync`` resyncs
    sharded leaves collectively (zero.resync) instead of broadcasting
    rank 0's shard, the state re-shards to the 2-worker layout, and the
    training invariant (w == step, every element) holds through the
    rollback."""
    procs, outs = _launch_elastic(
        3, extra_env={
            "HOROVOD_FAULT_INJECT": "kill:rank=1:step=3:code=17",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        }, worker=ZERO_WORKER)
    assert procs[1].returncode == 17, outs[1]
    for i in (0, 2):
        assert procs[i].returncode == 0, (i, outs[i])
        assert "step=8" in outs[i], (i, outs[i])
        assert "w=8" in outs[i], (i, outs[i])
        assert "size=2" in outs[i], (i, outs[i])
        assert "shard_world=2" in outs[i], (i, outs[i])
        restarts = float(outs[i].split(
            "elastic_restarts_total=")[1].split()[0])
        assert restarts >= 1, (i, outs[i])


def test_kill_mid_backward_with_buckets_in_flight():
    """Bucket-wise gradient release under elastic failure (ISSUE 12):
    rank 1 dies *inside* its second bucket release at step 3 — the first
    bucket's allreduce is already in flight and later buckets never
    arrive. The survivors' gather must fail every orphaned bucket token
    with WorkersDownError, the re-formed 2-worker generation finishes on
    the SAME plan object, and no fusion-buffer lease leaks across the
    failure (the worker exits 4 if any slab stays checked out)."""
    procs, outs = _launch_elastic(
        3, extra_env={
            "BUCKET_KILL_STEP": "3",
            "BUCKET_KILL_RANK": "1",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        }, worker=BUCKET_WORKER)
    assert procs[1].returncode == 17, outs[1]
    for i in (0, 2):
        assert procs[i].returncode == 0, (i, outs[i])
        assert "DONE" in outs[i], (i, outs[i])
        assert "step=6" in outs[i], (i, outs[i])
        assert "w=6" in outs[i], (i, outs[i])
        assert "size=2" in outs[i], (i, outs[i])
        assert "leases_leaked=0" in outs[i], (i, outs[i])
        # the bucketed path really exercised the wire: 3 buckets x steps
        released = int(outs[i].split("wire_released=")[1].split()[0])
        assert released >= 3 * 6, (i, outs[i])


def test_no_fault_runs_clean():
    """Same harness without injection: the elastic wrapper must be
    transparent when nothing fails (no spurious re-forms, generation 0)."""
    procs, outs = _launch_elastic(2, timeout=180)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "step=8" in out, out
        assert "generation=0" in out, out
        assert "elastic_restarts_total=0" in out, out
