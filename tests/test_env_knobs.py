"""Environment-knob contract: every HOROVOD_* var referenced in code is
documented, still exists when documented, and is registered in
horovod_tpu/utils/env.py (tools/check_env_knobs.py keeps the three
views from drifting)."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "tools", "check_env_knobs.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_env_knobs", CHECKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_env_knob_contract_holds():
    """The repo's actual contract: no undocumented, stale or
    unregistered knobs."""
    mod = _load_checker()
    undocumented, stale, unregistered = mod.check()
    assert not undocumented, (
        f"HOROVOD_* vars referenced in code but absent from docs/ and "
        f"README.md: {sorted(undocumented)}")
    assert not stale, (
        f"HOROVOD_* vars documented but no longer referenced in code: "
        f"{sorted(stale)}")
    assert not unregistered, (
        f"HOROVOD_* vars referenced in code but not registered in "
        f"horovod_tpu/utils/env.py (Config or ENV_DIRECT_KNOBS): "
        f"{sorted(unregistered)}")


def test_checker_cli_exit_codes(tmp_path):
    assert subprocess.run([sys.executable, CHECKER]).returncode == 0
    # a tree with drift in all three directions exits nonzero and names it
    (tmp_path / "horovod_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "horovod_tpu" / "a.py").write_text(
        'os.environ["HOROVOD_SECRET_KNOB"]\n')
    (tmp_path / "docs" / "a.md").write_text("`HOROVOD_REMOVED_KNOB`\n")
    out = subprocess.run([sys.executable, CHECKER, str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1
    assert "HOROVOD_SECRET_KNOB" in out.stderr
    assert "HOROVOD_REMOVED_KNOB" in out.stderr
    # the secret knob is also unregistered (no utils/env.py in the tree)
    assert "UNREGISTERED: HOROVOD_SECRET_KNOB" in out.stderr


def test_registration_check(tmp_path):
    """A documented knob still fails when utils/env.py doesn't list it;
    listing it in ENV_DIRECT_KNOBS (or as a constant) passes."""
    mod = _load_checker()
    (tmp_path / "horovod_tpu" / "utils").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "horovod_tpu" / "a.py").write_text(
        'os.environ["HOROVOD_POINT_OF_USE_KNOB"]\n')
    (tmp_path / "docs" / "a.md").write_text("`HOROVOD_POINT_OF_USE_KNOB`\n")
    (tmp_path / "horovod_tpu" / "utils" / "env.py").write_text(
        "ENV_DIRECT_KNOBS = ()\n")
    undocumented, stale, unregistered = mod.check(tmp_path)
    assert undocumented == set() and stale == set()
    assert unregistered == {"HOROVOD_POINT_OF_USE_KNOB"}
    (tmp_path / "horovod_tpu" / "utils" / "env.py").write_text(
        'ENV_DIRECT_KNOBS = ("HOROVOD_POINT_OF_USE_KNOB",)\n')
    assert mod.check(tmp_path) == (set(), set(), set())


def test_wildcards_and_fragments(tmp_path):
    mod = _load_checker()
    (tmp_path / "horovod_tpu" / "utils").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    # a wrapped string literal leaves a trailing-underscore fragment that
    # must not count as its own knob
    (tmp_path / "horovod_tpu" / "a.py").write_text(
        '"HOROVOD_LONG_KNOB_"\n"NAME"\n"HOROVOD_LONG_KNOB_NAME"\n'
        '"HOROVOD_FAMILY_MEMBER_A"\n"HOROVOD_FAMILY_MEMBER_B"\n')
    # docs cover the knob exactly and the family by wildcard prefix;
    # prose like HOROVOD_WITH[OUT]_* names a family, not a knob
    (tmp_path / "docs" / "a.md").write_text(
        "`HOROVOD_LONG_KNOB_NAME` and the `HOROVOD_FAMILY_*` knobs, "
        "HOROVOD_WITH[OUT]_* style.\n")
    (tmp_path / "horovod_tpu" / "utils" / "env.py").write_text(
        'ENV_DIRECT_KNOBS = ("HOROVOD_LONG_KNOB_NAME",\n'
        '                    "HOROVOD_FAMILY_MEMBER_A",\n'
        '                    "HOROVOD_FAMILY_MEMBER_B")\n')
    undocumented, stale, unregistered = mod.check(tmp_path)
    assert undocumented == set()
    assert stale == set()
    assert unregistered == set()
