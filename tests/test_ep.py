"""Expert parallelism: Switch MoE routing correctness + training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import ep, pp

DIM = 8
TOKENS = 16  # per device


def _expert_fn(params, h):
    return jnp.tanh(h @ params["w"])


def _expert_params(rng, n_exp):
    return pp.stack_stage_params(
        [{"w": jnp.asarray(rng.randn(DIM, DIM).astype(np.float32) * 0.5)}
         for _ in range(n_exp)])


class TestSwitchMoe:
    def _run(self, hvd, x, logits, stacked, capacity):
        def inner(stacked, x, logits):
            y, probs = ep.switch_moe(x, logits, _expert_fn, stacked,
                                     "local", capacity)
            return y, probs

        return jax.jit(jax.shard_map(
            inner, mesh=hvd.mesh(),
            in_specs=(P("local"), P("local"), P("local")),
            out_specs=(P("local"), P("local")), check_vma=False))(
            stacked, x, logits)

    def test_routing_matches_local_reference(self, hvd_flat):
        """EP output == locally computing every token through its argmax
        expert, weighted by the gate (capacity ample, no drops)."""
        n_exp = hvd_flat.local_size()
        rng = np.random.RandomState(0)
        stacked = _expert_params(rng, n_exp)
        x = jnp.asarray(rng.randn(n_exp * TOKENS, DIM).astype(np.float32))
        logits = jnp.asarray(
            rng.randn(n_exp * TOKENS, n_exp).astype(np.float32))

        y, probs = self._run(hvd_flat, x, logits, stacked,
                             capacity=TOKENS)  # ample

        probs_ref = jax.nn.softmax(logits, axis=-1)
        idx = np.asarray(jnp.argmax(probs_ref, -1))
        gate = np.asarray(jnp.take_along_axis(
            probs_ref, jnp.argmax(probs_ref, -1)[:, None], -1))[:, 0]
        experts = [np.asarray(w) for w in np.asarray(stacked["w"])]
        ref = np.stack([
            gate[t] * np.tanh(np.asarray(x[t]) @ experts[idx[t]])
            for t in range(x.shape[0])])
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)

    def test_capacity_drops_excess_tokens(self, hvd_flat):
        """Tokens beyond capacity produce zero output."""
        n_exp = hvd_flat.local_size()
        rng = np.random.RandomState(1)
        stacked = _expert_params(rng, n_exp)
        x = jnp.asarray(rng.randn(n_exp * TOKENS, DIM).astype(np.float32))
        # force ALL tokens to expert 0
        logits = jnp.tile(
            jnp.asarray([[10.0] + [0.0] * (n_exp - 1)], jnp.float32),
            (n_exp * TOKENS, 1))

        y, _ = self._run(hvd_flat, x, logits, stacked, capacity=2)
        y = np.asarray(y).reshape(n_exp, TOKENS, DIM)
        # per device: first 2 tokens kept, rest dropped to zero
        assert np.abs(y[:, :2]).min() > 0
        np.testing.assert_allclose(y[:, 2:], 0.0)

    def test_gradients_match_local_reference(self, hvd_flat):
        """EP grads (through dispatch scatter + two all_to_alls) == grads
        of the per-token local formulation."""
        n_exp = hvd_flat.local_size()
        rng = np.random.RandomState(3)
        stacked = _expert_params(rng, n_exp)
        gate_w = jnp.asarray(rng.randn(DIM, n_exp).astype(np.float32) * 0.3)
        x = jnp.asarray(rng.randn(n_exp * TOKENS, DIM).astype(np.float32))

        def ep_loss(stacked, gate_w):
            def inner(stacked, gate_w, x):
                y, _ = ep.switch_moe(x, x @ gate_w, _expert_fn, stacked,
                                     "local", capacity=TOKENS)
                return jax.lax.pmean(jnp.mean(y ** 2), "local")

            return jax.shard_map(
                inner, mesh=hvd_flat.mesh(),
                in_specs=(P("local"), P(), P("local")), out_specs=P(),
                check_vma=False)(stacked, gate_w, x)

        def ref_loss(stacked, gate_w):
            probs = jax.nn.softmax((x @ gate_w).astype(jnp.float32), -1)
            idx = jnp.argmax(probs, -1)
            gate = jnp.take_along_axis(probs, idx[:, None], -1)[:, 0]
            all_out = jnp.stack(
                [_expert_fn({"w": stacked["w"][e]}, x)
                 for e in range(n_exp)])  # (E, T, D)
            y = jnp.take_along_axis(
                all_out, idx[None, :, None], axis=0)[0] * gate[:, None]
            return jnp.mean(y ** 2)

        g_ep = jax.jit(jax.grad(ep_loss, argnums=(0, 1)))(stacked, gate_w)
        g_ref = jax.grad(ref_loss, argnums=(0, 1))(stacked, gate_w)
        for a, b in zip(jax.tree_util.tree_leaves(g_ep),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_router_size_mismatch_raises(self, hvd_flat):
        n_exp = hvd_flat.local_size()
        rng = np.random.RandomState(4)
        stacked = _expert_params(rng, n_exp)
        x = jnp.asarray(rng.randn(n_exp * 4, DIM).astype(np.float32))
        logits = jnp.zeros((n_exp * 4, n_exp * 2))  # wrong expert count
        import pytest as _pytest

        with _pytest.raises(ValueError, match="one expert per device"):
            self._run(hvd_flat, x, logits, stacked, capacity=4)

    def test_load_balance_loss_uniform_is_one(self, hvd_flat):
        probs = jnp.full((32, 4), 0.25)
        loss = ep.load_balance_loss(probs)
        np.testing.assert_allclose(float(loss), 1.0, rtol=1e-6)
        # concentrated routing scores worse
        conc = jax.nn.softmax(
            jnp.tile(jnp.asarray([[5.0, 0, 0, 0]]), (32, 1)))
        assert float(ep.load_balance_loss(conc)) > 1.0

    def test_moe_training_converges(self, hvd_flat):
        """Gate + experts train end to end through the all_to_all."""
        n_exp = hvd_flat.local_size()
        rng = np.random.RandomState(2)
        params = {
            "experts": _expert_params(rng, n_exp),
            "gate": jnp.asarray(rng.randn(DIM, n_exp).astype(np.float32)
                                * 0.1),
        }
        x = jnp.asarray(rng.randn(n_exp * TOKENS, DIM).astype(np.float32))
        target = jnp.asarray(np.tanh(rng.randn(n_exp * TOKENS, DIM))
                             .astype(np.float32))
        opt = optax.adam(5e-3)
        state = opt.init(params)

        def loss_fn(params, x, target):
            def inner(experts, gate, x, target):
                logits = x @ gate
                y, probs = ep.switch_moe(x, logits, _expert_fn, experts,
                                         "local", capacity=TOKENS)
                mse = jnp.mean((y - target) ** 2)
                aux = ep.load_balance_loss(probs, axis_name="local")
                return jax.lax.pmean(mse, "local") + 0.01 * aux

            return jax.shard_map(
                inner, mesh=hvd_flat.mesh(),
                in_specs=(P("local"), P(), P("local"), P("local")),
                out_specs=P(), check_vma=False)(
                params["experts"], params["gate"], x, target)

        @jax.jit
        def step(params, state, x, target):
            loss, g = jax.value_and_grad(loss_fn)(params, x, target)
            updates, state = opt.update(g, state, params)
            return loss, optax.apply_updates(params, updates), state

        losses = []
        for _ in range(300):
            loss, params, state = step(params, state, x, target)
            losses.append(float(loss))
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])
