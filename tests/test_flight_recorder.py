"""Flight recorder tests: ring semantics, dump triggers, straggler
attribution, and the launcher-side postmortem merge (ISSUE.md PR 4).

The multiprocess half (a killed worker leaving a readable dump naming
itself; an injected-slow rank leading the straggler gauge) lives in
tests/test_flight_recorder_multiprocess.py.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu import flight_recorder
from horovod_tpu.flight_recorder import SCHEMA, FlightRecorder
from horovod_tpu.utils.env import (DEFAULT_FLIGHT_RECORDER_CAPACITY,
                                   parse_flight_recorder)


@pytest.fixture
def rec(monkeypatch):
    """A private recorder instance so tests never disturb the module
    global the production code paths share."""
    monkeypatch.delenv("HOROVOD_FLIGHT_RECORDER", raising=False)
    monkeypatch.delenv("HOROVOD_FLIGHT_RECORDER_DIR", raising=False)
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    monkeypatch.delenv("HOROVOD_RENDEZVOUS_HTTP_ADDR", raising=False)
    monkeypatch.delenv("HOROVOD_RENDEZVOUS_HTTP_PORT", raising=False)
    return FlightRecorder()


class TestParseKnob:
    def test_default_on(self):
        assert parse_flight_recorder(None) == \
            (True, DEFAULT_FLIGHT_RECORDER_CAPACITY)
        assert parse_flight_recorder("") == \
            (True, DEFAULT_FLIGHT_RECORDER_CAPACITY)

    @pytest.mark.parametrize("v", ["0", "false", "no", "off", " OFF "])
    def test_disable(self, v):
        assert parse_flight_recorder(v)[0] is False

    def test_integer_sets_capacity(self):
        assert parse_flight_recorder("512") == (True, 512)
        # 1/true-ish keep the default capacity
        assert parse_flight_recorder("1") == \
            (True, DEFAULT_FLIGHT_RECORDER_CAPACITY)
        assert parse_flight_recorder("yes") == \
            (True, DEFAULT_FLIGHT_RECORDER_CAPACITY)


class TestRing:
    def test_ring_overwrites_oldest(self, rec, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "8")
        rec.configure()
        assert rec.capacity == 8
        for i in range(20):
            rec.emit("tick", i=i)
        evs = rec.events()
        assert len(evs) == 8
        assert [e["i"] for e in evs] == list(range(12, 20))
        assert all(e["kind"] == "tick" and "t" in e for e in evs)

    def test_configure_capacity_change_keeps_recent(self, rec, monkeypatch):
        for i in range(10):
            rec.emit("tick", i=i)
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "4")
        rec.configure()
        assert [e["i"] for e in rec.events()] == [6, 7, 8, 9]

    def test_disabled_emits_nothing(self, rec, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "0")
        rec.configure()
        rec.emit("tick")
        assert rec.events() == []

    def test_concurrent_emit_is_safe(self, rec, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER", "256")
        rec.configure()
        barrier = threading.Barrier(8)

        def hammer(tid):
            barrier.wait()
            for i in range(2000):
                rec.emit("hammer", tid=tid, i=i)

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        evs = rec.events()
        assert len(evs) == 256
        # every surviving event is a complete record, no torn writes
        assert all(e["kind"] == "hammer" and "tid" in e and "i" in e
                   for e in evs)


class TestDump:
    def test_snapshot_shape(self, rec):
        rec.emit("tick", i=1)
        rec.set_state_provider("thing", lambda: {"depth": 3})
        snap = rec.snapshot("unit")
        assert snap["schema"] == SCHEMA
        assert snap["reason"] == "unit"
        assert snap["state"]["thing"] == {"depth": 3}
        assert snap["events"][-1]["kind"] == "tick"
        assert "metrics" in snap and "pid" in snap and "host" in snap

    def test_failing_state_provider_does_not_block(self, rec):
        rec.set_state_provider("bad", lambda: 1 / 0)
        snap = rec.snapshot("unit")
        assert "state provider failed" in snap["state"]["bad"]

    def test_dump_path_variants(self, rec, tmp_path):
        rec.launch_rank = 3
        assert rec._dump_path(str(tmp_path)) == \
            str(tmp_path / "flight-rank-3.json")
        assert rec._dump_path(str(tmp_path / "x-{rank}.json")) == \
            str(tmp_path / "x-3.json")
        assert rec._dump_path(str(tmp_path / "exact.json")) == \
            str(tmp_path / "exact.json")

    def test_dump_writes_file_and_history(self, rec, tmp_path):
        rec.emit("tick", i=1)
        rec.dump("first", path=str(tmp_path))
        rec.dump("second", path=str(tmp_path))
        # last dump wins the file; earlier reasons survive in history
        with open(tmp_path / "flight-rank-0.json") as f:
            doc = json.load(f)
        assert doc["reason"] == "second"
        assert [h["reason"] for h in doc["dump_history"]] == ["first"]
        assert doc["events"][-1]["kind"] == "tick"

    def test_dump_never_raises_on_bad_dir(self, rec):
        rec.dump("unit", path="/proc/does/not/exist/x.json")

    def test_dump_on_failure_rate_limited(self, tmp_path, monkeypatch):
        g = flight_recorder.recorder()
        monkeypatch.setattr(g, "enabled", True)
        monkeypatch.setattr(g, "dir", str(tmp_path))
        monkeypatch.setattr(g, "_dump_history", [])
        monkeypatch.setattr(g, "_last_failure_dump", 0.0)
        flight_recorder.dump_on_failure("one")
        flight_recorder.dump_on_failure("two")  # within 1s: suppressed
        assert [h["reason"] for h in g._dump_history] == ["one"]

    def test_dump_debug_state_public_api(self, tmp_path, monkeypatch):
        import horovod_tpu as hvd
        g = flight_recorder.recorder()
        monkeypatch.setattr(g, "dir", "")
        snap = hvd.dump_debug_state()
        assert snap["schema"] == SCHEMA
        out = tmp_path / "dbg.json"
        hvd.dump_debug_state(path=str(out))
        assert json.load(open(out))["reason"] == "on_demand"


class TestDebugEndpoint:
    def test_debug_route_serves_snapshot(self):
        from horovod_tpu.metrics import registry
        reg = registry()
        port = reg.serve(0)
        try:
            flight_recorder.emit("debug_probe", x=1)
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/debug" % port, timeout=5) as resp:
                assert resp.headers["Content-Type"] == "application/json"
                doc = json.loads(resp.read())
            assert doc["schema"] == SCHEMA
            assert doc["reason"] == "debug_endpoint"
            assert any(e["kind"] == "debug_probe" for e in doc["events"])
        finally:
            reg.stop_server()


class TestRuntimeIntegration:
    def test_cycle_abort_emits_and_dumps(self, hvd, tmp_path, monkeypatch):
        from horovod_tpu.runtime.runtime import get_runtime
        rt = get_runtime()
        g = flight_recorder.recorder()
        monkeypatch.setattr(g, "dir", str(tmp_path))
        monkeypatch.setattr(g, "_last_failure_dump", 0.0)

        def boom(*a, **k):
            raise RuntimeError("injected cycle failure")

        monkeypatch.setattr(rt.controller, "compute_response_list", boom)
        h = hvd.allreduce_async(
            hvd.stack_per_worker(
                [np.ones((2,), "float32")] * hvd.size()),
            name="fr/abort")
        with pytest.raises(Exception):
            hvd.synchronize(h)
        deadline = time.monotonic() + 10
        path = tmp_path / ("flight-rank-%d.json" % g.launch_rank)
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        doc = json.load(open(path))
        assert doc["reason"] == "cycle_abort"
        aborts = [e for e in doc["events"] if e["kind"] == "cycle_abort"]
        assert aborts and "injected cycle failure" in aborts[-1]["error"]

    def test_init_registers_runtime_state_provider(self, hvd):
        snap = flight_recorder.debug_state()
        assert "runtime" in snap["state"]
        assert any(e["kind"] == "init" for e in snap["events"])


class TestStragglerTracker:
    def test_lag_ewma_names_slow_rank(self):
        from horovod_tpu.stall import StragglerTracker
        tr = StragglerTracker(world=3, report_seconds=0)
        for i in range(10):
            tr.observe("t%d" % i, {0: 100.0 + i, 1: 100.0 + i,
                                   2: 100.4 + i})
        ranking = tr.ranking()
        assert ranking[0][0] == 2
        assert ranking[0][1] == pytest.approx(0.4, abs=1e-6)
        assert tr.last_counts[2] == 10
        assert "rank 2=0.400s" in tr.lag_summary()
        # subset filter keeps only the wanted ranks
        assert tr.lag_summary(ranks=[0]).startswith("rank 0=")

    def test_report_emits_flight_event(self):
        from horovod_tpu.stall import StragglerTracker
        tr = StragglerTracker(world=2, report_seconds=0.001)
        tr._last_report = time.monotonic() - 60
        tr.observe("t", {0: 1.0, 1: 1.2})
        evs = flight_recorder.recorder().events()
        reports = [e for e in evs if e["kind"] == "straggler_report"]
        assert reports and reports[-1]["leader"] == 1


class _Req:
    def __init__(self, rank):
        self.rank = rank


class _Table:
    def __init__(self, pending, first):
        self._pending, self._first = pending, first

    def pending(self):
        return self._pending

    def first_request_time(self, name):
        return self._first.get(name)


class TestStallInspectorAttribution:
    def test_warning_enriched_with_lag(self, monkeypatch):
        from horovod_tpu import stall
        tr = stall.StragglerTracker(world=2, report_seconds=0)
        tr.lag_ewma = {1: 0.5}
        insp = stall.StallInspector(warning_time_seconds=0.0,
                                    shutdown_time_seconds=0.0)
        table = _Table({"grad/x": [_Req(0)]},
                       {"grad/x": time.monotonic() - 100})
        warnings = []
        monkeypatch.setattr(stall.log, "warning",
                            lambda fmt, *a: warnings.append(fmt % a))
        assert insp.check(table, world=2, straggler=tr) is False
        assert warnings and "rank 1=0.500s" in warnings[-1]
        warn = [e for e in flight_recorder.recorder().events()
                if e["kind"] == "stall_warning"]
        assert warn and warn[-1]["missing"] == [1]

    def test_elastic_shutdown_raises_with_ranks(self):
        from horovod_tpu.exceptions import WorkerStallError
        from horovod_tpu.stall import StallInspector
        insp = StallInspector(warning_time_seconds=0.0,
                              shutdown_time_seconds=0.001, elastic=True)
        table = _Table({"grad/x": [_Req(0)]},
                       {"grad/x": time.monotonic() - 100})
        with pytest.raises(WorkerStallError) as ei:
            insp.check(table, world=2)
        assert ei.value.ranks == (1,)
        down = [e for e in flight_recorder.recorder().events()
                if e["kind"] == "stall_shutdown"]
        assert down and down[-1]["ranks"] == [1]


# -- launcher-side postmortem -------------------------------------------------
def _dump(rank, events, offset=None, reason="test", metrics=None):
    return {"schema": SCHEMA, "rank": rank, "launch_rank": rank,
            "pid": 1000 + rank, "host": "host%d" % rank, "reason": reason,
            "wall_time": 0.0, "clock_offset_seconds": offset,
            "dump_history": [], "events": events, "state": {},
            "metrics": metrics or {}}


class TestPostmortem:
    def test_load_dumps_skips_garbage(self, tmp_path):
        (tmp_path / "flight-rank-0.json").write_text(
            json.dumps(_dump(0, [])))
        (tmp_path / "flight-rank-9.json").write_text("{truncated")
        (tmp_path / "unrelated.json").write_text("{}")
        dumps = flight_recorder.load_dumps(str(tmp_path))
        assert len(dumps) == 1 and dumps[0]["launch_rank"] == 0
        assert flight_recorder.load_dumps(str(tmp_path / "missing")) == []

    def test_merge_applies_clock_offsets(self):
        dumps = [
            _dump(0, [{"t": 10.0, "kind": "a"}], offset=5.0),
            _dump(1, [{"t": 12.0, "kind": "b"}], offset=0.0),
        ]
        merged = flight_recorder.merge_events(dumps)
        # rank 0's event lands at 15.0 merged time, after rank 1's 12.0
        assert [e["kind"] for e in merged] == ["b", "a"]
        assert merged[1]["t_merged"] == 15.0
        assert merged[0]["rank"] == 1

    def test_culprit_priority_kill_wins(self):
        dumps = [
            _dump(0, [{"t": 1, "kind": "workers_down", "ranks": [2]}]),
            _dump(1, [{"t": 1, "kind": "fault_inject", "action": "kill",
                       "rank": 1}]),
        ]
        rank, why = flight_recorder.suspect_culprit(dumps)
        assert rank == 1 and "injected kill" in why

    def test_culprit_from_workers_down_votes(self):
        dumps = [
            _dump(0, [{"t": 1, "kind": "workers_down", "ranks": [2]},
                      {"t": 2, "kind": "stall_shutdown", "ranks": [2]}]),
            _dump(1, [{"t": 1, "kind": "workers_down", "ranks": [2, 3]}]),
        ]
        rank, why = flight_recorder.suspect_culprit(dumps)
        assert rank == 2 and "workers_down" in why

    def test_culprit_from_straggler_lag(self):
        metrics = {"horovod_straggler_lag_seconds": {"values": [
            {"labels": {"rank": "0"}, "value": 0.01},
            {"labels": {"rank": "2"}, "value": 0.42},
        ]}}
        dumps = [_dump(0, [], metrics=metrics)]
        rank, why = flight_recorder.suspect_culprit(dumps)
        assert rank == "2" and "straggler lag" in why

    def test_culprit_none(self):
        assert flight_recorder.suspect_culprit([_dump(0, [])]) is None

    def test_format_postmortem(self):
        dumps = [
            _dump(0, [{"t": 10.0 + i, "kind": "tick", "i": i}
                      for i in range(50)], offset=0.0),
            _dump(1, [{"t": 100.0, "kind": "fault_inject", "action": "kill",
                       "rank": 1}], reason="fault_inject_kill"),
        ]
        text = flight_recorder.format_postmortem(dumps, last_n=10)
        assert "2 dumps" in text
        assert "rank 1: reason=fault_inject_kill" in text
        assert "earlier events omitted" in text
        assert "suspected culprit: rank 1 (recorded its own injected kill)" \
            in text
        # the tail carries the per-event extras
        assert "action=kill" in text


class TestCli:
    def test_postmortem_exits_nonzero_when_empty(self, tmp_path, capsys):
        from horovod_tpu.run.run import run_commandline
        assert run_commandline(["--postmortem", str(tmp_path)]) == 1
        assert "no flight-recorder dumps" in capsys.readouterr().err

    def test_postmortem_prints_report(self, tmp_path, capsys):
        from horovod_tpu.run.run import run_commandline
        (tmp_path / "flight-rank-0.json").write_text(json.dumps(
            _dump(0, [{"t": 1.0, "kind": "fault_inject", "action": "kill",
                       "rank": 0}])))
        assert run_commandline(["--postmortem", str(tmp_path)]) == 0
        assert "suspected culprit: rank 0" in capsys.readouterr().out

    def test_metrics_summary_exits_nonzero_when_empty(self, tmp_path,
                                                      capsys):
        from horovod_tpu.run.run import run_commandline
        assert run_commandline(["--metrics-summary", str(tmp_path)]) == 1
        assert "no metrics dump" in capsys.readouterr().err
