"""Flight-recorder acceptance scenarios over real processes (ISSUE.md
PR 4): a HOROVOD_FAULT_INJECT-killed worker must leave a readable dump
whose final events identify the dead rank (and the merged postmortem
must name it), and an injected-slow rank must lead the coordinator's
``horovod_straggler_lag_seconds`` gauge.

Reuses the elastic multiprocess harness: the pytest process hosts the
rendezvous HTTP store (standing in for the tpurun launcher), workers run
tests/elastic_worker.py over the socket/native transport.
"""

import json
import os

import pytest

from horovod_tpu.runtime.native import native_built
from test_elastic_multiprocess import _launch_elastic

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


def test_killed_rank_leaves_dump_and_postmortem_names_it(tmp_path, capsys):
    """Acceptance: rank 1 is hard-killed (os._exit) at step 3; its dump —
    written before the exit — must record the injected kill, survivors
    must record the worker loss, and ``tpurun --postmortem`` over the
    dump directory must name rank 1 as the suspected culprit."""
    flight_dir = tmp_path / "flight"
    procs, outs = _launch_elastic(
        3, extra_env={
            "HOROVOD_FAULT_INJECT": "kill:rank=1:step=3:code=17",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "HOROVOD_FLIGHT_RECORDER_DIR": str(flight_dir),
        })
    assert procs[1].returncode == 17, outs[1]
    for i in (0, 2):
        assert procs[i].returncode == 0, (i, outs[i])

    # the killed rank dumped before os._exit, naming its own death
    victim = json.load(open(flight_dir / "flight-rank-1.json"))
    assert victim["reason"] == "fault_inject_kill"
    kills = [e for e in victim["events"]
             if e["kind"] == "fault_inject" and e["action"] == "kill"]
    assert kills and kills[-1]["rank"] == 1 and kills[-1]["step"] == 3

    # every rank left a dump; the survivors recorded a failure-path dump
    # (the first of cycle_abort / worker_lost wins, the rest are
    # rate-limited), superseded by the clean-shutdown dump with the
    # earlier reason preserved in dump_history
    survivor_events, survivor_reasons = [], []
    for i in (0, 2):
        doc = json.load(open(flight_dir / ("flight-rank-%d.json" % i)))
        survivor_events.extend(doc["events"])
        survivor_reasons.append(doc["reason"])
        survivor_reasons.extend(h["reason"] for h in doc["dump_history"])
    assert any(e["kind"] == "workers_down" for e in survivor_events)
    assert any(e["kind"] == "elastic_reform" for e in survivor_events)
    assert {"cycle_abort", "worker_lost", "worker_stall"} & \
        set(survivor_reasons), survivor_reasons

    # the merged postmortem names the culprit
    from horovod_tpu.run.run import run_commandline
    assert run_commandline(["--postmortem", str(flight_dir)]) == 0
    out = capsys.readouterr().out
    assert "suspected culprit: rank 1 (recorded its own injected kill)" \
        in out
    assert "reason=fault_inject_kill" in out


def test_injected_slow_rank_leads_straggler_gauge():
    """Acceptance: rank 2 sleeps 0.3s at every step >= 2; the coordinator
    (rank 0) must attribute the lag to rank 2 via the
    horovod_straggler_lag_seconds EWMA. The response cache is disabled so
    every step renegotiates and stamps per-rank arrivals."""
    procs, outs = _launch_elastic(
        3, extra_env={
            "HOROVOD_FAULT_INJECT": "slow:rank=2:step=2:seconds=0.3",
            "HOROVOD_CACHE_CAPACITY": "0",
        })
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "step=8" in out, out

    lags = {}
    for line in outs[0].splitlines():
        if line.startswith("LAG rank="):
            parts = dict(kv.split("=") for kv in line.split()[1:])
            lags[int(parts["rank"])] = float(parts["value"])
    assert lags, "coordinator printed no straggler lag samples:\n" + outs[0]
    leader = max(lags, key=lags.get)
    assert leader == 2, lags
    assert lags[2] > 0.05, lags
    assert all(lags[r] < lags[2] for r in lags if r != 2), lags
