"""Goodput ledger (ISSUE 19): category accounting that sums to
wall-clock, the bounded incident ledger and replay attribution, the
surfaces (/goodput route + the JSON route index, merged-trace counter +
incident lanes, hvd_top panel, cross-rank postmortem report), the knob
plumbing, and the bench_compare goodput_fraction gate.

Tier-1 safe: everything here drives the tracker directly — no devices,
no timing sensitivity (spans are injected, not measured). The real
multiprocess acceptance (a killed rank's re-form downtime landing in
``elastic_reform`` on every survivor) is at the bottom, and the full
three-disruption attribution proof is tools/chaos_matrix.py's
``goodput_attribution`` cell.
"""

import json
import os
import socket
import subprocess
import sys
import urllib.request

import pytest

from horovod_tpu import flight_recorder, goodput

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracker():
    """A fresh GoodputTracker so tests never fight the singleton."""
    t = goodput.GoodputTracker()
    t.enabled = True
    t.rank, t.world = 0, 2
    t.start_epoch()
    yield t


def _age(t, seconds=3600.0):
    """Backdate the epoch so injected spans fit inside the wall-clock
    (no proportional scale-down) — and pin the first-work mark to the
    epoch so the synthetic past is not misread as startup time."""
    with t._lock:
        t._epoch -= seconds
        t._first_mark = t._epoch


def _rewind_step_mark(t, seconds):
    """Open a synthetic gap since the last accounted step, so injected
    step walls survive the frontier clamp without real sleeps."""
    with t._lock:
        if t._step_mark is not None:
            t._step_mark -= seconds


@pytest.fixture
def singleton():
    """The process-wide tracker, reset before and after (the /goodput
    route, hvd_top panel and bench goodput_rows read the singleton)."""
    t = goodput.tracker()
    saved = (t.enabled, t.rank, t.world, t.report_seconds)
    t.reset()
    t.enabled = True
    t.start_epoch()
    yield t
    t.reset()
    t.enabled, t.rank, t.world, t.report_seconds = saved


class TestAccounting:
    def test_categories_sum_to_wall_exactly(self, tracker):
        _age(tracker)
        tracker.record_step(0.5)
        tracker.record_span("ckpt_stall", 0.2)
        tracker.record_span("collective_stall", 0.1)
        led = tracker.ledger()
        total = led["productive_seconds"] + sum(
            led["badput_seconds"].values())
        # exact pre-rounding; the ledger rounds each entry to 6dp so the
        # recomposed sum can differ by a few ulps per category
        assert abs(total - led["wall_seconds"]) < 1e-4
        assert led["badput_seconds"]["ckpt_stall"] == pytest.approx(
            0.2, abs=1e-6)
        assert led["steps_productive"] == 1

    def test_remainder_lands_in_input_idle(self, tracker):
        tracker.record_step(1e-6)  # attribute ~nothing
        led = tracker.ledger()
        assert led["badput_seconds"].get("input_idle", 0.0) >= 0.0
        assert led["accounted_fraction"] <= 1.0

    def test_over_attribution_scales_down(self, tracker):
        # claim far more than elapsed: the ledger must scale to wall,
        # never report accounted > 1
        tracker.record_span("ckpt_stall", 3600.0)
        tracker.record_span("rollback", 3600.0)
        led = tracker.ledger()
        total = sum(led["badput_seconds"].values()) \
            + led["productive_seconds"]
        assert total == pytest.approx(led["wall_seconds"], abs=1e-4)
        assert led["accounted_fraction"] == pytest.approx(1.0, abs=1e-6)
        # proportionality survives the scale-down
        bp = led["badput_seconds"]
        assert bp["ckpt_stall"] == pytest.approx(bp["rollback"], rel=1e-3)

    def test_unknown_category_dropped(self, tracker):
        tracker.record_span("coffee_break", 5.0)
        assert "coffee_break" not in tracker.ledger()["badput_seconds"]

    def test_disabled_tracker_records_nothing(self, tracker):
        tracker.enabled = False
        tracker.record_step(0.5)
        tracker.record_span("ckpt_stall", 0.2)
        tracker.note_incident("rollback", 1.0)
        led = tracker.ledger()
        assert led["steps_productive"] == 0
        assert led["incidents"] == []

    def test_startup_is_gap_before_first_work(self, tracker):
        import time

        time.sleep(0.05)
        tracker.record_step(0.01)
        led = tracker.ledger()
        assert led["badput_seconds"].get(
            "startup_compile", 0.0) >= 0.04

    def test_nothing_attributed_is_all_startup(self, tracker):
        import time

        time.sleep(0.02)
        led = tracker.ledger()
        assert led["badput_seconds"]["startup_compile"] == pytest.approx(
            led["wall_seconds"], abs=1e-4)
        assert led["goodput_fraction"] == 0.0

    def test_exposed_comm_split_out_of_step(self, tracker):
        _age(tracker)
        tracker.record_step(0.5, exposed_comm=0.1)
        led = tracker.ledger()
        assert led["productive_seconds"] == pytest.approx(0.4, abs=1e-6)
        assert led["badput_seconds"]["exposed_comm"] == pytest.approx(
            0.1, abs=1e-6)

    def test_profiler_claim_clamped_to_gap(self, tracker):
        # frontier guard: a measured step wall can never exceed the
        # unattributed gap since the previous accounted step
        _age(tracker)
        tracker.record_step(1e-4)
        _rewind_step_mark(tracker, 0.01)  # real gap: 10 ms
        tracker.record_step(3600.0)  # absurd measurement
        led = tracker.ledger()
        assert led["productive_seconds"] <= led["wall_seconds"] + 1e-6
        assert led["productive_seconds"] < 1.0  # clamped to the gap
        assert led["steps_productive"] == 2

    def test_commit_source_excludes_badput_spans(self, tracker):
        import time

        tracker.record_step(1e-4)  # pin the step frontier
        time.sleep(0.03)
        tracker.record_span("elastic_reform", 0.025)  # inside the gap
        tracker.record_step()  # commit-style: claims gap MINUS the span
        led = tracker.ledger()
        assert led["badput_seconds"]["elastic_reform"] == pytest.approx(
            0.025, abs=1e-6)
        # productive gets the remainder of the gap, not the whole gap
        assert led["productive_seconds"] < led["wall_seconds"] - 0.02


class TestServePlane:
    def test_serve_steps_are_productive(self, tracker):
        _age(tracker)
        tracker.record_serve_step(0.2, tokens=4)
        led = tracker.ledger()
        assert led["productive_seconds"] == pytest.approx(0.2, abs=1e-6)
        assert led["serve_blocks"] == 1

    def test_preemption_reattributes_net_zero(self, tracker):
        _age(tracker)
        tracker.record_serve_step(0.4, tokens=4)  # cost 0.1 s/token
        before = tracker.ledger()
        tracker.note_serve_preempted(2)
        led = tracker.ledger()
        assert led["badput_seconds"]["serve_preempted"] == pytest.approx(
            0.2, abs=1e-6)
        assert led["productive_seconds"] == pytest.approx(
            before["productive_seconds"] - 0.2, abs=1e-6)

    def test_preemption_clamped_to_available_productive(self, tracker):
        _age(tracker)
        tracker.record_serve_step(0.1, tokens=1)  # cost 0.1 s/token
        tracker.note_serve_preempted(1000)
        led = tracker.ledger()
        assert led["productive_seconds"] == pytest.approx(0.0, abs=1e-6)
        assert led["badput_seconds"]["serve_preempted"] == pytest.approx(
            0.1, abs=1e-6)

    def test_prefill_does_not_poison_token_cost(self, tracker):
        tracker.record_serve_step(0.4, tokens=4)   # decode: cost 0.1
        tracker.record_serve_step(9.0, tokens=0)   # prefill: no tokens
        with tracker._lock:
            assert tracker._serve_token_cost == pytest.approx(0.1)


class TestIncidents:
    def test_incident_record_shape_and_counts(self, tracker):
        _age(tracker)
        tracker.note_incident(
            "elastic_reform", 2.5, generation=1, culprit_rank=3,
            linked_events=["elastic_reform", "workers_down"],
            detail="rank 3 lost")
        (inc,) = tracker.incidents()
        assert inc["cause"] == "elastic_reform"
        assert inc["duration_s"] == pytest.approx(2.5)
        assert inc["generation"] == 1
        assert inc["culprit_rank"] == 3
        assert inc["linked_events"] == ["elastic_reform", "workers_down"]
        led = tracker.ledger()
        assert led["incident_counts"] == {"elastic_reform": 1}
        assert led["badput_seconds"]["elastic_reform"] == pytest.approx(
            2.5, abs=1e-4)

    def test_incident_emits_flight_event(self, tracker):
        before = len([e for e in flight_recorder.recorder().events()
                      if e.get("kind") == "goodput_incident"])
        tracker.note_incident("rollback", 0.5, culprit_rank=1)
        events = [e for e in flight_recorder.recorder().events()
                  if e.get("kind") == "goodput_incident"]
        assert len(events) - before == 1
        assert events[-1]["cause"] == "rollback"
        assert events[-1]["culprit_rank"] == 1

    def test_ledger_is_bounded(self, tracker):
        tracker.set_incident_capacity(4)
        for i in range(10):
            tracker.note_incident("rollback", 0.01, detail="inc %d" % i)
        incidents = tracker.incidents()
        assert len(incidents) == 4
        assert incidents[-1]["detail"] == "inc 9"  # newest kept
        # counts keep the full history even as the ring rolls
        assert tracker.ledger()["incident_counts"]["rollback"] == 10

    def test_unknown_cause_coerced(self, tracker):
        tracker.note_incident("meteor_strike", 1.0)
        assert tracker.incidents()[0]["cause"] == "rollback"


class TestReplayAttribution:
    def test_replayed_steps_charged_to_incident(self, tracker):
        _age(tracker)
        tracker.record_step(0.1)  # one honest step
        tracker.note_incident("rollback", 0.5, replay_steps=2)
        _rewind_step_mark(tracker, 1.0)
        tracker.record_step(0.2)  # replays: badput, not productive
        _rewind_step_mark(tracker, 1.0)
        tracker.record_step(0.2)
        _rewind_step_mark(tracker, 1.0)
        tracker.record_step(0.1)  # countdown exhausted: productive again
        led = tracker.ledger()
        assert led["steps_productive"] == 2
        assert led["steps_replayed"] == 2
        assert led["badput_seconds"]["rollback"] == pytest.approx(
            0.5 + 0.4, abs=1e-4)
        (inc,) = tracker.incidents()
        assert inc["steps_replayed"] == 2
        assert inc["replayed_seconds"] == pytest.approx(0.4, abs=1e-4)

    def test_replay_charged_to_arming_cause(self, tracker):
        _age(tracker)
        tracker.record_step(0.1)
        tracker.note_incident("elastic_reform", 0.2, replay_steps=1)
        _rewind_step_mark(tracker, 1.0)
        tracker.record_step(0.3)
        led = tracker.ledger()
        assert led["badput_seconds"]["elastic_reform"] == pytest.approx(
            0.5, abs=1e-4)
        assert "rollback" not in led["badput_seconds"]


class TestConfigure:
    def test_knobs_and_provider_registration(self, singleton, monkeypatch):
        monkeypatch.setenv("HOROVOD_GOODPUT", "1")
        monkeypatch.setenv("HOROVOD_GOODPUT_INCIDENTS", "7")
        monkeypatch.setenv("HOROVOD_GOODPUT_REPORT_SECONDS", "30")
        goodput.configure(rank=3, world=4)
        assert singleton.enabled is True
        assert singleton.rank == 3 and singleton.world == 4
        assert singleton.report_seconds == 30.0
        with singleton._lock:
            assert singleton._incidents.maxlen == 7
        assert "goodput" in flight_recorder._recorder._providers
        monkeypatch.setenv("HOROVOD_GOODPUT", "0")
        goodput.configure()
        assert singleton.enabled is False
        assert "goodput" not in flight_recorder._recorder._providers
        monkeypatch.setenv("HOROVOD_GOODPUT", "1")
        goodput.configure()  # restore for the fixture teardown

    def test_epoch_survives_reconfigure(self, singleton):
        with singleton._lock:
            epoch = singleton._epoch
        goodput.configure(rank=0, world=2)  # elastic reinit path
        with singleton._lock:
            assert singleton._epoch == epoch

    def test_goodput_state_document(self, singleton):
        singleton.record_step(0.1)
        state = goodput.goodput_state()
        assert state["enabled"] is True
        assert state["steps_productive"] == 1
        assert isinstance(state["samples"], list) and state["samples"]


class TestMetricsRoutes:
    def test_get_goodput_route(self, singleton):
        from horovod_tpu.metrics import MetricsRegistry

        singleton.record_step(0.05)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/goodput" % port, timeout=5) as r:
                doc = json.loads(r.read().decode())
            assert doc["steps_productive"] == 1
            assert 0.0 <= doc["goodput_fraction"] <= 1.0
            assert "badput_seconds" in doc and "samples" in doc
        finally:
            reg.stop_server()

    def test_root_serves_route_index(self):
        """ISSUE 19 satellite: bare GET / (and /debug/routes) answers a
        JSON index of every route instead of 404."""
        from horovod_tpu.metrics import MetricsRegistry, route_index

        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            for path in ("/", "/debug/routes"):
                with urllib.request.urlopen(
                        "http://127.0.0.1:%d%s" % (port, path),
                        timeout=5) as r:
                    assert r.headers.get_content_type() == \
                        "application/json"
                    doc = json.loads(r.read().decode())
                for route in ("/metrics", "/goodput", "/comms", "/slo",
                              "/memory", "/healthz", "/serve"):
                    assert route in doc["routes"], (path, doc)
            assert route_index()["routes"] == doc["routes"]
        finally:
            reg.stop_server()

    def test_unknown_route_still_404s(self):
        from horovod_tpu.metrics import MetricsRegistry

        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    "http://127.0.0.1:%d/nope" % port, timeout=5)
            assert err.value.code == 404
        finally:
            reg.stop_server()


def _dump(rank, gp_state):
    return {"schema": flight_recorder.SCHEMA, "rank": rank,
            "launch_rank": rank, "pid": 1000 + rank,
            "host": "host%d" % rank, "reason": "test", "wall_time": 0.0,
            "clock_offset_seconds": 0.0, "dump_history": [], "events": [],
            "state": {"goodput": gp_state}, "metrics": {}}


def _gp_state(rank, wall, productive, badput, incidents=(),
              replayed=0):
    return {"rank": rank, "world": 2, "wall_time": 0.0,
            "enabled": True, "wall_seconds": wall,
            "goodput_fraction": productive / wall,
            "accounted_fraction": 1.0,
            "productive_seconds": productive,
            "badput_seconds": badput, "steps_productive": 10,
            "steps_replayed": replayed, "serve_blocks": 0,
            "incident_counts": {}, "incidents": list(incidents)}


class TestPostmortemReport:
    def test_cross_rank_report(self):
        dumps = [
            _dump(0, _gp_state(0, 100.0, 80.0,
                               {"ckpt_stall": 5.0, "input_idle": 15.0})),
            _dump(1, _gp_state(
                1, 100.0, 60.0,
                {"elastic_reform": 30.0, "input_idle": 10.0},
                incidents=[{"cause": "elastic_reform", "wall_time": 1.0,
                            "duration_s": 30.0, "generation": 1,
                            "culprit_rank": 2, "steps_replayed": 3,
                            "replayed_seconds": 6.0,
                            "linked_events": [], "detail": None}],
                replayed=3)),
        ]
        text = goodput.format_goodput_report(dumps)
        assert "=== goodput report (2 ranks) ===" in text
        assert "rank 0: goodput 80.0% of 100.0s" in text
        assert "3 step(s) replayed" in text
        # fleet 140/200 time-weighted
        assert "fleet goodput: 70.0% (time-weighted across 2 ranks)" \
            in text
        assert "dominant badput: elastic_reform (30.0s" in text
        assert ("costliest incident: elastic_reform on rank 1 — 36.0s "
                "(gen 1, 3 step(s) replayed, culprit rank 2)") in text

    def test_report_empty_without_goodput_state(self):
        dumps = [_dump(0, None)]
        dumps[0]["state"] = {}
        assert goodput.format_goodput_report(dumps) == ""

    def test_format_postmortem_embeds_goodput_section(self):
        dumps = [_dump(0, _gp_state(0, 10.0, 9.0, {"input_idle": 1.0}))]
        text = flight_recorder.format_postmortem(dumps)
        assert "=== goodput report" in text
        assert "rank 0: goodput 90.0%" in text


class TestHvdTop:
    def _import_hvd_top(self):
        repo_tools = os.path.join(_REPO, "tools")
        if repo_tools not in sys.path:
            sys.path.insert(0, repo_tools)
        import hvd_top
        return hvd_top

    def test_goodput_panel_against_live_endpoint(self, singleton):
        from horovod_tpu.metrics import MetricsRegistry

        hvd_top = self._import_hvd_top()
        singleton.record_step(0.05)
        singleton.note_incident("rollback", 0.2, culprit_rank=1)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            ep = "127.0.0.1:%d" % port
            panel = hvd_top.render_goodput([ep])
            assert "top badput" in panel.splitlines()[0]
            assert "rollback" in panel
            assert "last incident: rollback" in panel
            # the route index drives panel selection
            routes = hvd_top.discover_routes([ep])
            assert "/goodput" in routes
            assert hvd_top.panel_wanted(routes, "/goodput")
            assert not hvd_top.panel_wanted(routes, "/made_up")
        finally:
            reg.stop_server()

    def test_goodput_panel_empty_without_endpoint(self):
        hvd_top = self._import_hvd_top()
        assert hvd_top.render_goodput(["127.0.0.1:1"]) == ""
        # no index reachable: fall back to probing every panel
        assert hvd_top.discover_routes(["127.0.0.1:1"]) is None
        assert hvd_top.panel_wanted(None, "/anything")


class TestMergedTrace:
    def test_fraction_counter_and_incident_instants(self, tmp_path):
        from horovod_tpu import profiler

        t0 = 1700000000.0
        dump = {"schema": "horovod-profiler-v1", "rank": 0,
                "launch_rank": 0, "clock_offset_seconds": 0.0,
                "steps": [], "trace_events": [
                    {"ph": "X", "pid": 0, "tid": 0, "ts": t0 * 1e6,
                     "dur": 1e4, "name": "step 0"}],
                "flight_events": [],
                "goodput_samples": [[t0, 0.9], [t0 + 1.0, 0.5],
                                    ["bogus", None]],
                "goodput_incidents": [
                    {"cause": "elastic_reform", "wall_time": t0 + 0.5,
                     "duration_s": 2.0, "generation": 1,
                     "culprit_rank": 2, "steps_replayed": 0},
                    {"cause": "rollback"},  # no wall_time: skipped
                ]}
        with open(tmp_path / "profile-rank-0.json", "w") as f:
            json.dump(dump, f)
        out, _ = profiler.merge_profile_dir(str(tmp_path))
        events = json.load(open(out))["traceEvents"]
        counters = [e for e in events
                    if e.get("name") == "goodput fraction"]
        assert len(counters) == 2  # malformed row skipped
        assert all(e["ph"] == "C" for e in counters)
        assert counters[0]["args"] == {"productive": 0.9}
        instants = [e for e in events
                    if str(e.get("name", "")).startswith("incident:")]
        assert len(instants) == 1  # wall_time-less record skipped
        assert instants[0]["ph"] == "i"
        assert instants[0]["name"] == "incident: elastic_reform"
        assert instants[0]["args"]["culprit_rank"] == 2

    def test_profiler_snapshot_carries_goodput_trails(self, singleton):
        from horovod_tpu import profiler

        singleton.record_step(0.01)
        singleton.note_incident("rollback", 0.1)
        snap = profiler._profiler.snapshot()
        assert snap["goodput_samples"]
        assert snap["goodput_incidents"][-1]["cause"] == "rollback"


# ---------------------------------------------------------------------------
# bench surfaces
# ---------------------------------------------------------------------------

@pytest.fixture
def bench_compare():
    repo_tools = os.path.join(_REPO, "tools")
    if repo_tools not in sys.path:
        sys.path.insert(0, repo_tools)
    import bench_compare as mod

    return mod


def _artifact(path, rows):
    tail = "\n".join(["bench log noise"] + [json.dumps(r) for r in rows])
    with open(path, "w") as f:
        json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                   "tail": tail}, f)
    return str(path)


_BASE_ROW = {"metric": "images/sec/chip (ResNet-50 synthetic)",
             "value": 2000.0, "unit": "images/sec/chip"}


def test_bench_compare_collapsed_goodput_fails(bench_compare, tmp_path,
                                               capsys):
    """ISSUE 19 satellite: goodput_fraction is a higher-is-better
    fraction — a candidate that burns its wall-clock on stalls and
    replays gates like a throughput regression even when the step
    latency headline holds."""
    base_row = dict(_BASE_ROW, goodput_fraction=0.92)
    base = _artifact(tmp_path / "base.json", [base_row])
    cand_row = dict(base_row, goodput_fraction=0.55)
    cand = _artifact(tmp_path / "cand.json", [cand_row])
    assert bench_compare.main([base, cand]) == 1
    out = capsys.readouterr().out
    assert "goodput_fraction" in out
    assert "higher is better" in out


def test_bench_compare_goodput_row_clean_pass(bench_compare, tmp_path,
                                              capsys):
    row = dict(_BASE_ROW, goodput_fraction=0.92)
    base = _artifact(tmp_path / "base.json", [row])
    cand = _artifact(tmp_path / "cand.json", [dict(row)])
    assert bench_compare.main([base, cand]) == 0
    assert "[goodput_fraction]" in capsys.readouterr().out


@pytest.fixture
def bench(hvd):
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import bench as bench_mod

    return bench_mod


def test_goodput_suite_tiny(bench, capsys):
    """ISSUE 19 satellite shape: ``bench.py --goodput --tiny`` runs the
    interleaved tracker-off/tracker-on A/B and reports the overhead
    headline as one JSON line with zero steady-state compiles."""
    result = bench.goodput_main(tiny=True)
    assert result["tiny"] is True
    assert result["unit"] == "%"
    assert result["goal"] == "< 1%"
    assert result["p50_ms_goodput_off"] > 0
    assert result["p50_ms_goodput_on"] > 0
    assert result["steady_state_compiles"] == 0
    assert result["steps_productive"] > 0
    assert 0.0 <= result["goodput_fraction"] <= 1.0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == result["value"]


# ---------------------------------------------------------------------------
# multiprocess: a killed rank's downtime lands in elastic_reform
# ---------------------------------------------------------------------------

from horovod_tpu.run.rendezvous import RendezvousServer  # noqa: E402
from horovod_tpu.runtime.native import native_built  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(not native_built(),
                    reason="native transport not built")
def test_reform_downtime_attributed_on_survivors(tmp_path):
    """Kill rank 1 mid-run: every survivor's ledger must carry the
    re-form downtime in ``elastic_reform`` (with an incident naming the
    lost rank as culprit) while still accounting >= 90% of wall-clock."""
    world, total = 3, 5
    worker = os.path.join(_REPO, "tools", "chaos_worker.py")
    server = RendezvousServer(host="127.0.0.1")
    http_port = server.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_MIN_WORKERS": "2",
                "HOROVOD_ELASTIC_SETTLE_SECONDS": "0.3",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "HOROVOD_FAULT_INJECT": "kill:rank=1:step=2:code=17",
                "HOROVOD_FLIGHT_RECORDER_DIR": str(tmp_path),
                "CHAOS_TOTAL_STEPS": str(total),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        results = {}
        for rank, proc in enumerate(procs):
            out, _ = proc.communicate(timeout=120)
            want = 17 if rank == 1 else 0
            assert proc.returncode == want, \
                f"rank {rank} exited {proc.returncode}:\n{out[-2000:]}"
            for line in out.splitlines():
                if line.startswith("CHAOS_RESULT "):
                    results[rank] = json.loads(
                        line[len("CHAOS_RESULT "):])
        assert sorted(results) == [0, 2]
        for rank, res in results.items():
            assert res["step"] == total, res
            assert res["generation"] >= 1, res
            assert res["goodput_badput"].get("elastic_reform", 0) > 0, res
            assert res["goodput_accounted"] >= 0.9, res
            assert res["goodput_incidents"].get("elastic_reform") == 1, res
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
