"""Bucket-wise gradient release (ISSUE 12): partition order, bit-parity
with the unbucketed exchange, overlap accounting, zero steady-state
compiles, accumulation composition, and failure cleanup.

The load-bearing guarantees: (1) the bucketed wire path is bit-identical
to the unbucketed path for sum/avg across dtypes — bucketing changes
WHEN bytes move, never WHAT they reduce to; (2) released buckets ride
the PR-3 pipelined executor, so the profiler's hidden-comm accounting
rises with bucketed release and stays ~0 at pipeline depth 1; (3) a
failed bucket token keeps its dispatch/drain stamps and releases its
fusion-buffer lease — elastic re-forms start clean.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.parallel import buckets as buckets_mod
from horovod_tpu.parallel import dp
from horovod_tpu.runtime import message as msg, types


def _plan(**kw):
    kw.setdefault("bucket_bytes", 1024)
    return buckets_mod.GradReleasePlan(**kw)


class TestPartition:
    def test_reverse_topological_order(self, hvd):
        plan = _plan(bucket_bytes=1)  # one bucket per leaf
        params = {"a": jnp.zeros(4), "b": jnp.zeros(4), "c": jnp.zeros(4)}
        jax.grad(lambda p: sum(x.sum() for x in plan.tag(p).values()))(
            params)
        buckets = plan.buckets()
        # flatten order is a,b,c -> release order must be c,b,a
        flat_order = [i for b in buckets for i in b]
        assert flat_order == [2, 1, 0]

    def test_bucket_sizing(self, hvd):
        plan = _plan(bucket_bytes=64 * 4)  # 64 f32 elems per bucket
        params = {f"p{i}": jnp.zeros(32, jnp.float32) for i in range(6)}
        jax.grad(lambda p: sum(x.sum() for x in plan.tag(p).values()))(
            params)
        assert [len(b) for b in plan.buckets()] == [2, 2, 2]

    def test_tree_shape_change_rejected(self, hvd):
        plan = _plan()
        plan.tag({"a": jnp.zeros(4)})
        with pytest.raises(ValueError, match="changed shape"):
            plan.tag({"a": jnp.zeros(4), "b": jnp.zeros(4)})

    def test_quantum_rounding(self, hvd, monkeypatch):
        from horovod_tpu.utils import env as env_mod

        monkeypatch.setenv("HOROVOD_GRAD_BUCKET_BYTES", "100000")
        q = env_mod.DEFAULT_FUSION_BUCKET_QUANTUM_BYTES
        assert buckets_mod.bucket_bytes_from_env() % q == 0
        assert buckets_mod.bucket_bytes_from_env() >= 100000


class TestBitParity:
    """Bucketed == unbucketed, bitwise, for sum/avg across dtypes (the
    world is a power of two, so identical-row stacked reduction is an
    exact exponent shift)."""

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("average", [True, False])
    def test_grad_parity(self, hvd, dtype, average):
        plan = _plan(bucket_bytes=256, average=average)
        params = {"w": jnp.linspace(-2, 2, 256).astype(dtype),
                  "b": jnp.linspace(0.5, 1.5, 64).astype(dtype)}

        def loss(p):
            # sum of squares: non-constant per-element gradients (2x), so
            # any wire-side reduction rounding breaks the bit comparison
            t = plan.tag(p)
            return ((t["w"].astype(jnp.float32) ** 2).sum()
                    + (t["b"].astype(jnp.float32) ** 2).sum())

        grads = jax.grad(loss)(params)
        bucketed = plan.gather(grads)
        unbucketed = dp.allreduce_gradients(grads, average=average)
        for k in params:
            a = np.asarray(bucketed[k]).view(np.uint8)
            b = np.asarray(unbucketed[k]).view(np.uint8)
            assert np.array_equal(a, b), f"{k} not bit-identical"

    def test_i32_sum_parity(self, hvd):
        # int payloads can't come from jax.grad; feed the hooks directly
        # (the wire path is identical)
        plan = _plan(bucket_bytes=256, average=False)
        tree = {"n": jnp.arange(128, dtype=jnp.int32)}
        leaves, _ = jax.tree_util.tree_flatten(tree)
        plan._ensure_partition(leaves)
        plan._begin_pass()
        for b in reversed(plan.buckets()):
            for i in b:
                plan._on_grad(i, leaves[i])
        bucketed = plan.gather(tree)
        unbucketed = dp.allreduce_gradients(tree, average=False)
        assert np.array_equal(np.asarray(bucketed["n"]),
                              np.asarray(unbucketed["n"]))
        assert bucketed["n"].dtype == jnp.int32

    def test_zero_steady_state_compiles(self, hvd):
        from horovod_tpu.runtime import executor as executor_mod

        plan = _plan(bucket_bytes=512)
        params = {"w": jnp.ones(512, jnp.float32),
                  "v": jnp.ones(256, jnp.float32)}

        def one_step(s):
            g = jax.grad(lambda p: sum(
                (x * s).sum() for x in plan.tag(p).values()))(params)
            return plan.gather(g)

        for s in range(3):  # warmup: compile the size-bucketed programs
            one_step(float(s + 1))
        before = executor_mod._PROGRAM_COMPILES.value
        for s in range(4):
            one_step(float(s + 10))
        assert executor_mod._PROGRAM_COMPILES.value == before


class TestOverlapAccounting:
    """Satellite 3: comm_hidden_fraction ~0 at pipeline depth 1 / single
    bucket, rises with bucketed release; failure paths keep their
    dispatch/drain stamps."""

    def _bucketed_step(self, plan, params, salt):
        g = jax.grad(lambda p: sum(
            (x * salt).sum() for x in plan.tag(p).values()))(params)
        return plan.gather(g)

    def test_depth1_single_bucket_fully_exposed(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_CYCLE_PIPELINE_DEPTH", "1")
        monkeypatch.setenv("HOROVOD_PROFILE", "1")
        hvd.shutdown()
        hvd.init(mesh_shape=(2, 4))
        try:
            from horovod_tpu import profiler

            profiler.configure()
            plan = _plan(bucket_bytes=1 << 24)  # everything in one bucket
            params = {"w": jnp.ones(4096, jnp.float32)}
            self._bucketed_step(plan, params, 1.0)  # warmup/compile
            with profiler.step("depth1") as rec:
                self._bucketed_step(plan, params, 2.0)
            comm = rec.breakdown["comm"]
            assert comm["total_seconds"] > 0
            assert comm["dispatches"] >= 1
            assert comm["hidden_fraction"] < 0.05
        finally:
            monkeypatch.delenv("HOROVOD_PROFILE", raising=False)
            from horovod_tpu import profiler

            profiler.configure()
            hvd.shutdown()

    def test_bucketed_release_hides_comm(self, monkeypatch):
        # small fusion threshold so same-cycle buckets keep their own
        # dispatches (fuse_responses joins by dtype+op, not priority)
        monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1024")
        monkeypatch.setenv("HOROVOD_PROFILE", "1")
        hvd.shutdown()
        hvd.init(mesh_shape=(2, 4))
        try:
            from horovod_tpu import profiler

            profiler.configure()
            plan = _plan(bucket_bytes=16 * 1024)
            params = {f"p{i}": jnp.ones(8192, jnp.float32)
                      for i in range(6)}
            self._bucketed_step(plan, params, 1.0)  # warmup/compile
            with profiler.step("bucketed") as rec:
                self._bucketed_step(plan, params, 2.0)
            comm = rec.breakdown["comm"]
            assert comm["total_seconds"] > 0
            assert comm["dispatches"] >= 2  # one per released bucket
            assert comm["hidden_fraction"] > 0.0
            assert comm["hidden_fraction_bytes"] > 0.0
        finally:
            monkeypatch.delenv("HOROVOD_PROFILE", raising=False)
            from horovod_tpu import profiler

            profiler.configure()
            hvd.shutdown()

    def test_stamps_survive_failure(self, hvd):
        from horovod_tpu.runtime.runtime import get_runtime

        ex = get_runtime().executor
        base = ex.fusion_buffers.allocated_bytes()
        entries = [types.TensorTableEntry(
            name="buckets/fail/t0",
            tensor=hvd.stack_per_worker(
                [np.ones((256,), "float32")] * hvd.size()),
            reduce_op=types.REDUCE_SUM)]
        pend = ex.dispatch(
            msg.Response(types.ALLREDUCE, [e.name for e in entries]),
            entries)
        pend.fail(types.Status.UnknownError("injected bucket failure"))
        assert pend.t_disp_end is not None
        assert pend.t_drain_start is not None
        assert pend.t_drain_start >= pend.t_disp_end
        # the lease went back: a failed bucket token must not strand its
        # fusion-buffer slab (elastic re-forms reuse the buffer)
        assert ex.fusion_buffers.allocated_bytes() == base


class TestAccumulation:
    def test_only_final_pass_releases(self, hvd):
        plan = _plan(bucket_bytes=256, every_k=3)
        params = {"w": jnp.ones(256, jnp.float32)}

        def grad_for(s):
            return jax.grad(lambda p: (plan.tag(p)["w"] * s).sum())(params)

        assert plan.gather(grad_for(1.0)) is None
        assert plan.gather(grad_for(2.0)) is None
        assert plan.wire_stats()["released"] == 0
        out = plan.gather(grad_for(6.0))
        assert out is not None
        np.testing.assert_allclose(np.asarray(out["w"]), 3.0, rtol=1e-6)
        assert plan.wire_stats()["released"] >= 1

    def test_state_resets_between_steps(self, hvd):
        plan = _plan(bucket_bytes=256)
        params = {"w": jnp.ones(128, jnp.float32)}
        for s in (1.0, 5.0):
            g = jax.grad(lambda p: (plan.tag(p)["w"] * s).sum())(params)
            out = plan.gather(g)
            np.testing.assert_allclose(np.asarray(out["w"]), s, rtol=1e-6)
        assert plan._grads == {} and plan._released == []


class TestFailureCleanup:
    def test_gather_drains_and_resets_on_failure(self, hvd):
        plan = _plan(bucket_bytes=512)
        params = {"a": jnp.ones(512, jnp.float32),
                  "b": jnp.ones(512, jnp.float32)}
        g = jax.grad(lambda p: sum(
            x.sum() for x in plan.tag(p).values()))(params)
        assert plan._released  # buckets in flight

        class _Boom:
            def wait(self):
                raise hvd.WorkersDownError("injected", ranks=(1,))

        # poison the FIRST released handle; gather must still drain the
        # rest, reset, and re-raise
        bucket_idx, pairs, t_release, wire_bytes = plan._released[0]
        plan._released[0] = (bucket_idx, [(pairs[0][0], _Boom())]
                             + pairs[1:], t_release, wire_bytes)
        with pytest.raises(hvd.WorkersDownError):
            plan.gather(g)
        assert plan._released == [] and plan._grads == {}
        # next step works on the same plan
        g2 = jax.grad(lambda p: sum(
            2.0 * x.sum() for x in plan.tag(p).values()))(params)
        out = plan.gather(g2)
        np.testing.assert_allclose(np.asarray(out["a"]), 2.0, rtol=1e-6)

    def test_abort_clears_in_flight(self, hvd):
        plan = _plan(bucket_bytes=512)
        params = {"a": jnp.ones(512, jnp.float32)}
        jax.grad(lambda p: plan.tag(p)["a"].sum())(params)
        assert plan._released
        plan.abort()
        assert plan._released == [] and plan._grads == {}


class TestTracedLanes:
    def test_shard_map_pmean_with_barriers(self, hvd):
        from jax.sharding import PartitionSpec as P

        plan = _plan(bucket_bytes=256)
        params = {"a": jnp.ones(128, jnp.float32),
                  "b": jnp.ones(128, jnp.float32)}

        def per_device(x, p):
            def loss(p):
                t = plan.tag(p)
                return (t["a"] * x.sum()).sum() + t["b"].sum()

            return jax.grad(loss)(p)

        f = jax.shard_map(per_device, mesh=hvd.mesh(),
                          in_specs=(P(hvd.GLOBAL_AXES), P()),
                          out_specs=P())
        x = jnp.arange(float(hvd.size()))
        g = plan.gather(f(x, params))
        np.testing.assert_allclose(np.asarray(g["a"]),
                                   float(np.mean(np.asarray(x))),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g["b"]), 1.0, rtol=1e-6)

    def test_plain_jit_identity(self, hvd):
        plan = _plan(bucket_bytes=256)

        @jax.jit
        def gradfn(p):
            return jax.grad(lambda q: (plan.tag(q)["a"] * 3.0).sum())(p)

        g = plan.gather(gradfn({"a": jnp.ones(64, jnp.float32)}))
        np.testing.assert_allclose(np.asarray(g["a"]), 3.0, rtol=1e-6)


class TestIntegration:
    def test_prereduced_scope_skips_exchange(self, hvd):
        grads = {"w": jnp.full((32,), 2.0)}
        with buckets_mod.prereduced():
            out = dp.allreduce_gradients(grads)
        assert out is grads
        assert not buckets_mod.is_prereduced()

    def test_training_step_with_plan_matches_without(self, hvd):
        import optax

        from horovod_tpu import training
        from horovod_tpu.models.mnist import MnistConvNet

        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        rng = np.random.RandomState(0)
        images = jnp.asarray(rng.rand(4, 28, 28, 1), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 10, (4,)), jnp.int32)

        def run(grad_release):
            state = training.create_train_state(model, opt, (1, 28, 28, 1),
                                                broadcast=False)
            step = training._make_one_step(
                model, opt, training._default_loss_fn,
                grad_release=grad_release)
            loss, params, _stats, _opt = step(
                state.params, state.batch_stats, state.opt_state,
                images, labels)
            return float(loss), params

        loss_plain, p_plain = run(None)
        loss_plan, p_plan = run(_plan(bucket_bytes=4096))
        assert loss_plain == pytest.approx(loss_plan, rel=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(p_plain),
                        jax.tree_util.tree_leaves(p_plan)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-5, atol=1e-7)

    def test_grouped_allreduce_async_roundtrip(self, hvd):
        xs = [hvd.stack_per_worker(
            [np.full((64,), float(i + j), "float32")
             for i in range(hvd.size())]) for j in range(3)]
        handles = hvd.grouped_allreduce_async(
            xs, names=[f"gaa/t{j}" for j in range(3)], reduce_op="sum")
        outs = [hvd.synchronize(h) for h in handles]
        world = hvd.size()
        for j, o in enumerate(outs):
            expect = sum(float(i + j) for i in range(world))
            np.testing.assert_allclose(np.asarray(o), expect, rtol=1e-6)

    def test_add_group_atomic_duplicate(self, hvd):
        from horovod_tpu.runtime.runtime import get_runtime
        from horovod_tpu.runtime.tensor_queue import DuplicateNameError

        rt = get_runtime()
        x = hvd.stack_per_worker(
            [np.ones((32,), "float32")] * hvd.size())
        h = rt.enqueue_allreduce_group(["dupe/a"], [x], reduce_op="sum")
        with pytest.raises(DuplicateNameError):
            rt.enqueue_allreduce_group(["dupe/b", "dupe/a"], [x, x],
                                       reduce_op="sum")
        # all-or-nothing: "dupe/b" must NOT be stranded in the table
        assert rt.queue.peek("dupe/b") is None
        hvd.synchronize(h[0])

    def test_dp_eager_submission_reverse_topological(self, hvd,
                                                     monkeypatch):
        from horovod_tpu.ops import collectives

        seen = []
        real = collectives.grouped_allreduce

        def spy(tensors, **kw):
            seen.append([int(t.shape[-1]) for t in tensors])
            return real(tensors, **kw)

        monkeypatch.setattr(collectives, "grouped_allreduce", spy)
        grads = {"a": jnp.ones(8), "b": jnp.ones(16), "c": jnp.ones(32)}
        dp.allreduce_gradients(grads)
        # flatten order a(8), b(16), c(32) -> submitted last layer first
        assert seen and seen[0] == [32, 16, 8]
