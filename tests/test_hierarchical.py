"""Hierarchical (two-level ICI/DCN) collective tests on the virtual
(2, 4) CPU mesh.

The two-level RS→AR→AG decomposition (reference:
NCCLHierarchicalAllreduce, ops/nccl_operations.cc:150-346; hierarchical
allgather mpi_operations.cc:168-314; knobs common.h:75-76) must be
numerically identical to the flat path — the difference is which wires the
bytes ride.
"""

import numpy as np
import pytest

import horovod_tpu  # noqa: F401  (conftest provides the hvd fixture)


@pytest.fixture
def hvd_hier(hvd, monkeypatch):
    """Re-init with hierarchical knobs on (env-driven, like tpurun
    --hierarchical-allreduce)."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "1")
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield hvd
    hvd.shutdown()


class TestHierarchicalAllreduce:
    def test_matches_flat_average(self, hvd_hier):
        hvd = hvd_hier
        x = hvd.stack_per_worker(
            [np.full((5, 3), float(r), np.float32) for r in range(8)])
        out = np.asarray(hvd.allreduce(x))
        np.testing.assert_allclose(out, 3.5)

    def test_matches_flat_sum(self, hvd_hier):
        hvd = hvd_hier
        x = hvd.stack_per_worker(
            [np.full((7,), float(r + 1), np.float32) for r in range(8)])
        out = np.asarray(hvd.allreduce(x, average=False))
        np.testing.assert_allclose(out, sum(range(1, 9)))

    def test_padding_when_not_divisible(self, hvd_hier):
        # 5 elements over local=4 needs padding inside the RS/AG phases
        hvd = hvd_hier
        vals = [np.arange(5, dtype=np.float32) + r for r in range(8)]
        x = hvd.stack_per_worker(vals)
        out = np.asarray(hvd.allreduce(x))
        np.testing.assert_allclose(out, np.mean(np.stack(vals), axis=0),
                                   rtol=1e-6)

    def test_min_max_fall_back_to_flat(self, hvd_hier):
        hvd = hvd_hier
        x = hvd.stack_per_worker(
            [np.full((4,), float(r), np.float32) for r in range(8)])
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x, op=hvd.Min)),
                                   0.0)
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x, op=hvd.Max)),
                                   7.0)

    def test_named_async_fused_hierarchical(self, hvd_hier):
        """The enqueue runtime's fused program takes the two-level path."""
        hvd = hvd_hier
        handles = [
            hvd.allreduce_async(
                hvd.stack_per_worker(
                    [np.full((6,), float(r * (i + 1)), np.float32)
                     for r in range(8)]),
                name=f"hier/{i}")
            for i in range(3)
        ]
        for i, h in enumerate(handles):
            out = np.asarray(hvd.synchronize(h))
            np.testing.assert_allclose(
                out, np.mean([r * (i + 1) for r in range(8)]))

    def test_flat_when_mesh_single_level(self, hvd, monkeypatch):
        # (1, 8) mesh: no cross axis — hierarchical silently degrades
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        hvd.shutdown()
        hvd.init(mesh_shape=(1, 8))
        x = hvd.stack_per_worker(
            [np.full((3,), float(r), np.float32) for r in range(8)])
        np.testing.assert_allclose(np.asarray(hvd.allreduce(x)), 3.5)
        hvd.shutdown()


class TestHierarchicalAllgather:
    def test_matches_flat(self, hvd_hier):
        hvd = hvd_hier
        vals = [np.full((2, 3), float(r), np.float32) for r in range(8)]
        out = np.asarray(hvd.allgather(hvd.stack_per_worker(vals)))
        np.testing.assert_allclose(out, np.concatenate(vals, axis=0))

    def test_rank_order_preserved(self, hvd_hier):
        # worker order must be global rank order, not per-level order
        hvd = hvd_hier
        vals = [np.array([[r * 10.0]], np.float32) for r in range(8)]
        out = np.asarray(hvd.allgather(hvd.stack_per_worker(vals)))
        np.testing.assert_allclose(out[:, 0], [r * 10.0 for r in range(8)])


class TestAutotuneSweepsHierarchical:
    def test_sweep_includes_hierarchical_on_two_level_mesh(
            self, hvd, monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        hvd.shutdown()
        hvd.init(mesh_shape=(2, 4))
        try:
            from horovod_tpu.runtime.runtime import get_runtime

            pm = get_runtime().param_manager
            assert pm is not None
            assert "hierarchical_allreduce" in pm._sweep
            assert "hierarchical_allgather" in pm._sweep
        finally:
            hvd.shutdown()
