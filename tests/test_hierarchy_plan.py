"""Hierarchical HOST-ring collectives: plan formation, sweep gating,
and multiprocess numerical parity (ISSUE 18).

tests/test_hierarchical.py covers the two-level decomposition on the
XLA mesh path; this file covers its host TCP-ring port
(`runtime/hierarchy.py`): how ranks group into slices, when the
topology gates the autotune sweep, and — over four real worker
processes on the native wire — that the three-phase decomposition
bit-matches the flat ring on exactly-representable payloads, that the
compressed cross hop stays within the wire dtype's rounding, and that
every rank ends bit-identical to its peers even with compression on
(the PR-10 cross-rank digest contract).
"""

import json
import os
import socket
import subprocess
import sys
import types

import numpy as np
import pytest

from horovod_tpu.runtime import hierarchy
from horovod_tpu.runtime.executor import Executor
from horovod_tpu.runtime.native import native_built


def _net(world, rank, hosts=None):
    """A wire-free stand-in: explicit-group-size planning never touches
    the transport, and the hostname path only calls ``allgatherv``."""
    net = types.SimpleNamespace(world=world, rank=rank)
    if hosts is not None:
        net.allgatherv = lambda payload: [h.encode() for h in hosts]
    return net


class TestBuildPlan:
    def test_explicit_group_size_tiles_contiguously(self):
        plan = hierarchy.build_plan(_net(6, 3), group_size=2)
        assert plan.enabled
        assert (plan.num_groups, plan.group_size) == (3, 2)
        assert plan.members == (2, 3)          # rank 3's slice
        assert plan.cross_members == (1, 3, 5)  # slot-1 ranks, ring order
        assert (plan.group_index, plan.local_index) == (1, 1)
        assert plan.source == "env"

    @pytest.mark.parametrize("world,gsize", [
        (3, 0),   # world too small for two levels at all
        (6, 4),   # does not tile: 6 % 4 != 0
        (4, 4),   # one group is no hierarchy
        (4, 1),   # groups of one are no hierarchy
    ])
    def test_degenerate_topologies_fall_back_flat(self, world, gsize):
        plan = hierarchy.build_plan(_net(world, 0), group_size=gsize)
        assert not plan.enabled
        assert plan.source == "flat"

    def test_host_derived_groups_by_hostname(self):
        hosts = ["a", "a", "b", "b", "c", "c"]
        plan = hierarchy.build_plan(_net(6, 2, hosts), group_size=0)
        assert plan.enabled
        assert (plan.num_groups, plan.group_size) == (3, 2)
        assert plan.members == (2, 3)           # the "b" host
        assert plan.cross_members == (0, 2, 4)  # slot 0 of each host
        assert plan.source == "hosts"

    def test_host_derived_unequal_hosts_fall_back_flat(self):
        # 2+3+1 ranks per host: the cross ring can't pair one member
        # per slice at each slot
        hosts = ["a", "a", "b", "b", "b", "c"]
        plan = hierarchy.build_plan(_net(6, 0, hosts), group_size=0)
        assert not plan.enabled


class TestWireDtype:
    def test_codec_names(self):
        import ml_dtypes

        assert hierarchy.wire_dtype_from_name("none") is None
        assert hierarchy.wire_dtype_from_name("") is None
        for alias in ("fp16", "bf16", "bfloat16"):
            assert hierarchy.wire_dtype_from_name(alias) \
                == np.dtype(ml_dtypes.bfloat16)
        assert hierarchy.wire_dtype_from_name("ieee_fp16") \
            == np.dtype(np.float16)
        with pytest.raises(ValueError):
            hierarchy.wire_dtype_from_name("fp8")


class TestSweepGating:
    """The ISSUE-18 gating fix: `hierarchical_available` must be a
    static topology predicate on the HOST-RING plane too — the old
    mesh-only check meant a multi-host socket job never saw its
    hierarchical knobs join the autotune sweep."""

    def _exec(self, world, gsize):
        return types.SimpleNamespace(
            net=types.SimpleNamespace(world=world),
            _spmd_world=False,
            _hier_group_size=lambda: gsize)

    def test_host_ring_world_that_tiles_is_available(self):
        assert Executor.hierarchical_available(self._exec(4, 2))
        assert Executor.hierarchical_available(self._exec(6, 3))

    def test_auto_grouping_is_sweepable_at_world_ge_4(self):
        # group size 0 (hostname-derived) COULD split any world >= 4 —
        # the knob joins the sweep and a flat-resolving plan is a no-op
        assert Executor.hierarchical_available(self._exec(4, 0))
        assert not Executor.hierarchical_available(self._exec(2, 0))

    def test_non_tiling_group_size_is_unavailable(self):
        assert not Executor.hierarchical_available(self._exec(6, 4))
        assert not Executor.hierarchical_available(self._exec(4, 4))


# ---------------------------------------------------------------------------
# multiprocess parity over the native wire
# ---------------------------------------------------------------------------

WORLD = 4


def _parity_worker():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ml_dtypes

    from horovod_tpu.runtime.native import NetComm

    rank = int(os.environ["HOROVOD_RANK"])
    world = int(os.environ["HOROVOD_SIZE"])
    net = NetComm(rank, world, "127.0.0.1",
                  int(os.environ["HIER_TEST_PORT"]), 20000)
    plan = hierarchy.build_plan(net, 2)
    checks = {"plan": plan.enabled and plan.num_groups == 2
                      and plan.group_size == 2}
    rng = np.random.default_rng(7)  # same stream on every rank

    # bit parity vs the mathematically exact sum on payloads where fp
    # addition order can't bite — including n=37, which leaves uneven
    # (and empty) ring chunks at k=2
    for dtype in (np.float32, np.int32):
        for n in (8, 37, 1024):
            base = rng.integers(-50, 50, size=(world, n)).astype(dtype)
            buf = base[rank].copy()
            hierarchy.hier_allreduce(net, plan, buf, "sum")
            checks[f"sum_{np.dtype(dtype).name}_{n}"] = \
                bool(np.array_equal(buf, base.sum(axis=0)))

    for op, red in (("max", np.max), ("min", np.min),
                    ("product", np.prod)):
        base = rng.integers(1, 4, size=(world, 16)).astype(np.float32)
        buf = base[rank].copy()
        hierarchy.hier_allreduce(net, plan, buf, op)
        checks[op] = bool(np.array_equal(buf, red(base, axis=0)))

    bf16 = np.dtype(ml_dtypes.bfloat16)
    # small ints are exactly representable in bf16: the compressed hop
    # must be bit-exact, not merely close
    base = rng.integers(-8, 8, size=(world, 64)).astype(np.float32)
    buf = base[rank].copy()
    hierarchy.hier_allreduce(net, plan, buf, "sum", wire_dtype=bf16)
    checks["bf16_exact"] = bool(np.array_equal(buf, base.sum(axis=0)))

    # general floats: error bounded by the wire dtype's rounding, and
    # all ranks bit-identical (the cross-rank digest contract)
    base = rng.standard_normal((world, 256)).astype(np.float32)
    buf = base[rank].copy()
    hierarchy.hier_allreduce(net, plan, buf, "sum", wire_dtype=bf16)
    checks["bf16_err"] = float(np.max(np.abs(buf - base.sum(axis=0))))
    blobs = net.allgatherv(buf.tobytes())
    checks["bf16_agree"] = bool(all(b == blobs[0] for b in blobs))

    # reduce-scatter keeps the flat chunk convention: rank r gets chunk r
    n = 4 * world * 3
    base = rng.integers(-20, 20, size=(world, n)).astype(np.float32)
    chunk = hierarchy.hier_reducescatter(net, plan, base[rank].copy(),
                                         "sum")
    c = n // world
    checks["rs"] = bool(np.array_equal(
        chunk, base.sum(axis=0)[rank * c:(rank + 1) * c]))

    merged = [json.loads(b.decode())
              for b in net.allgatherv(json.dumps(checks).encode())]
    if rank == 0:
        print("CHECKS " + json.dumps(merged), flush=True)
    net.close()


@pytest.mark.skipif(not native_built(),
                    reason="native transport not built")
def test_multiprocess_parity_and_compression_bounds():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    try:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rank in range(WORLD):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       HOROVOD_RANK=str(rank),
                       HOROVOD_SIZE=str(WORLD),
                       HIER_TEST_PORT=str(port),
                       PYTHONPATH=os.pathsep.join(
                           p for p in (repo,
                                       os.environ.get("PYTHONPATH"))
                           if p))
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = [p.communicate(timeout=120)[0] for p in procs]
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, \
                f"rank {rank} exited {p.returncode}:\n{out[-2000:]}"
        merged = None
        for out in outs:
            for line in out.splitlines():
                if line.startswith("CHECKS "):
                    merged = json.loads(line[len("CHECKS "):])
        assert merged is not None, "no CHECKS line:\n" + "\n".join(outs)
        assert len(merged) == WORLD
        for rank, checks in enumerate(merged):
            err = checks.pop("bf16_err")
            # 256-term sum through a bf16 wire (~8 mantissa bits):
            # comfortably under 0.1 absolute for N(0,1) payloads,
            # and never exactly zero rounding on random floats
            assert 0 < err < 0.1, (rank, err)
            bad = {k: v for k, v in checks.items() if v is not True}
            assert not bad, (rank, bad)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


if __name__ == "__main__":
    if "--worker" in sys.argv:
        _parity_worker()
