"""Numerical integrity plane (ISSUE.md PR 10): digests, agreement vote,
fault injection grammar, spike guard, and rollback accounting.

The multiprocess halves (real digest exchange over the socket ring,
in-place rollback with real checkpoints) live in
tests/test_integrity_multiprocess.py and tools/chaos_matrix.py; this
module covers the single-controller paths and the pure logic.
"""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import exceptions
from horovod_tpu.integrity import digest, guards, inject, rollback

jnp = pytest.importorskip("jax.numpy")


@pytest.fixture(autouse=True)
def _fresh_integrity_state():
    """Integrity state is process-global (cadence counters, one-shot
    injection latches, the default guard, the replay budget) — every
    test starts and ends clean."""
    digest.reset()
    inject.reset()
    guards.reset()
    rollback.reset()
    yield
    digest.reset()
    inject.reset()
    guards.reset()
    rollback.reset()


@pytest.fixture
def integrity_on(monkeypatch):
    monkeypatch.setenv("HOROVOD_INTEGRITY", "1")
    monkeypatch.setenv("HOROVOD_INTEGRITY_INTERVAL", "1")


# ---------------------------------------------------------------------------
# digest primitives
# ---------------------------------------------------------------------------

class TestDigestPrimitives:
    def test_nonfinite_count(self):
        assert digest.nonfinite_count(np.zeros(4, np.float32)) == 0
        assert digest.nonfinite_count(
            np.array([1.0, np.nan, np.inf, -np.inf], np.float32)) == 3
        # integers cannot go non-finite, by definition
        assert digest.nonfinite_count(np.arange(8, dtype=np.int32)) == 0

    def test_nonfinite_count_bf16(self):
        x = jnp.array([1.0, 2.0, 3.0], jnp.bfloat16)
        assert digest.nonfinite_count(np.asarray(x)) == 0
        y = np.asarray(x).copy()
        inject.corrupt_nan(y)
        assert digest.nonfinite_count(y) == 1

    def test_checksum_bitwise(self):
        a = np.arange(16, dtype=np.float32)
        assert digest.checksum(a) == digest.checksum(a.copy())
        b = a.copy()
        inject.corrupt_bitflip(b)
        assert digest.checksum(b) != digest.checksum(a)
        # -0.0 == 0.0 numerically but is a different byte pattern: the
        # digest is an SDC detector, so it must see the difference
        assert digest.checksum(np.array([0.0], np.float32)) != \
            digest.checksum(np.array([-0.0], np.float32))

    def test_vote(self):
        assert digest.vote([7, 7, 7]) == (False, None)
        assert digest.vote([7, 9, 7]) == (True, 1)
        assert digest.vote([9, 7, 7, 7]) == (True, 0)
        # a 1-vs-1 split cannot say who corrupted
        assert digest.vote([7, 9]) == (True, None)
        # nor can a multi-rank minority
        assert digest.vote([7, 7, 9, 9, 7]) == (True, None)
        # two distinct single-rank minorities: unattributable
        assert digest.vote([7, 7, 9, 5]) == (True, None)

    def test_verify_clean(self):
        digest.verify([(0, 42), (0, 42), (0, 42)], bucket="b")

    def test_verify_nonfinite_names_contributor(self):
        with pytest.raises(exceptions.NumericalError) as ei:
            digest.verify([(0, 1), (3, 1), (0, 1)], bucket="fused[8]",
                          tensor="grad/w")
        assert ei.value.suspect_rank == 1
        assert ei.value.bucket == "fused[8]"
        assert ei.value.tensor == "grad/w"

    def test_verify_nonfinite_outranks_divergence(self):
        # a NaN usually propagates to CRC *agreement*; when both signals
        # fire, the input digest is the attribution that matters
        with pytest.raises(exceptions.NumericalError) as ei:
            digest.verify([(2, 1), (0, 9), (0, 9)], bucket="b")
        assert not isinstance(ei.value, exceptions.CollectiveIntegrityError)
        assert ei.value.suspect_rank == 0

    def test_verify_divergence_votes_suspect(self):
        with pytest.raises(exceptions.CollectiveIntegrityError) as ei:
            digest.verify([(0, 7), (0, 7), (0, 9)], bucket="ring[12]")
        assert ei.value.suspect_rank == 2

    def test_verify_local(self):
        digest.verify_local(0, bucket="b")
        with pytest.raises(exceptions.NumericalError) as ei:
            digest.verify_local(4, bucket="zero.grads", tensor="leaf[1]",
                                suspect_rank=5)
        assert ei.value.suspect_rank == 5

    def test_cadence(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_INTEGRITY", "1")
        monkeypatch.setenv("HOROVOD_INTEGRITY_INTERVAL", "3")
        hits = [digest.cadence_due("lane") for _ in range(7)]
        assert hits == [True, False, False, True, False, False, True]
        # interval 0 disables; master switch off disables
        monkeypatch.setenv("HOROVOD_INTEGRITY_INTERVAL", "0")
        assert not digest.cadence_due("lane")
        monkeypatch.setenv("HOROVOD_INTEGRITY_INTERVAL", "1")
        monkeypatch.delenv("HOROVOD_INTEGRITY")
        assert not digest.cadence_due("lane")


# ---------------------------------------------------------------------------
# fault-injection grammar
# ---------------------------------------------------------------------------

class TestInjectGrammar:
    def test_parse(self):
        spec = inject.parse_clause("bitflip:1")
        assert (spec.action, spec.rank, spec.after) == ("bitflip", 1, 0)
        spec = inject.parse_clause(" nan : 3 : after=5 ")
        assert (spec.action, spec.rank, spec.after) == ("nan", 3, 5)
        with pytest.raises(ValueError):
            inject.parse_clause("bitflip")  # no rank
        with pytest.raises(ValueError):
            inject.parse_clause("nan:0:steps=2")  # unknown key
        with pytest.raises(ValueError):
            inject.parse_clause("melt:0")

    def test_composes_with_process_fault_grammar(self, monkeypatch):
        from horovod_tpu.elastic import fault_inject

        monkeypatch.setenv(
            "HOROVOD_FAULT_INJECT",
            "kill:rank=1:step=3:code=17;bitflip:0:after=2")
        inject.reset()
        # each module sees only its own clauses
        spec = fault_inject.spec_from_env()
        assert spec is not None and spec.action == "kill"
        specs = inject.specs_from_env()
        assert len(specs) == 1 and specs[0].action == "bitflip"

    def test_after_countdown_and_one_shot(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FAULT_INJECT", "nan:5:after=2")
        inject.reset()
        assert inject.plan_dispatch_any() is None
        assert inject.plan_dispatch_any() is None
        assert inject.plan_dispatch_any() == ("nan", 5)
        assert inject.plan_dispatch_any() is None  # one-shot spent

    def test_plan_dispatch_filters_by_launch_rank(self, monkeypatch):
        from horovod_tpu.elastic import fault_inject

        monkeypatch.setenv("HOROVOD_FAULT_INJECT", "bitflip:1")
        monkeypatch.setattr(fault_inject, "_initial_rank", 0)
        inject.reset()
        assert inject.plan_dispatch() is None  # we are rank 0, target is 1
        monkeypatch.setattr(fault_inject, "_initial_rank", 1)
        inject.reset()
        assert inject.plan_dispatch() == "bitflip"

    def test_corruptors(self):
        buf = np.ones(4, np.float32)
        inject.corrupt_nan(buf)
        assert np.isnan(buf[0]) and buf[1] == 1.0
        buf = np.ones(4, np.float32)
        before = buf.copy()
        inject.corrupt_bitflip(buf)
        assert not np.array_equal(buf.view(np.uint8), before.view(np.uint8))


# ---------------------------------------------------------------------------
# step guard
# ---------------------------------------------------------------------------

class TestStepGuard:
    def test_warmup_accepts_everything_finite(self):
        g = guards.StepGuard(sigma=3.0, skip_budget=2, warmup=5)
        assert all(g.observe(v) for v in (100.0, 1.0, 50.0, 2.0, 80.0))

    def test_nonfinite_skipped_even_during_warmup(self):
        g = guards.StepGuard(skip_budget=5)
        assert not g.observe(float("nan"))
        assert not g.observe(float("inf"))
        assert g.observe(1.0)
        assert g.consecutive_skips == 0  # a clean step resets the streak

    def test_constant_stream_never_trips(self):
        g = guards.StepGuard(sigma=3.0, warmup=3)
        assert all(g.observe(2.5) for _ in range(50))

    def test_spike_skipped_and_drop_is_not_a_spike(self):
        g = guards.StepGuard(sigma=3.0, skip_budget=10, warmup=5)
        for v in (1.0, 1.1, 0.9, 1.0, 1.05, 0.95):
            assert g.observe(v)
        assert not g.observe(1e6)  # blow-up: skip
        assert g.observe(1e-4)    # collapse toward zero: progress, accept

    def test_skip_budget_exhaustion_raises(self):
        g = guards.StepGuard(skip_budget=2, name="loss")
        assert not g.observe(float("nan"))
        assert not g.observe(float("nan"))
        with pytest.raises(exceptions.NumericalError) as ei:
            g.observe(float("nan"))
        assert ei.value.tensor == "loss"

    def test_skipped_metric_counted(self):
        before = guards._SKIPPED.value
        guards.StepGuard().observe(float("nan"))
        assert guards._SKIPPED.value == before + 1

    def test_guard_gradients_flags_bad_leaf(self):
        assert guards.guard_gradients(
            {"a": np.ones(3, np.float32), "b": np.zeros(2, np.float32)})
        guards.reset()
        assert not guards.guard_gradients(
            {"a": np.ones(3, np.float32),
             "b": np.array([1.0, np.nan], np.float32)})


# ---------------------------------------------------------------------------
# rollback accounting
# ---------------------------------------------------------------------------

class _FakeState:
    """Duck-typed elastic state: records which restore path ran. The
    real ArrayState restore paths are exercised end-to-end in
    tests/test_integrity_multiprocess.py."""

    def __init__(self, ckpt_dir=""):
        self._ckpt_dir = ckpt_dir
        self.step = 7
        self.waited = False
        self.loaded = False
        self.resets = 0

    def checkpoint_wait(self):
        self.waited = True

    def load_latest(self):
        self.loaded = True
        self.step = 3
        return 3

    def on_reset(self):
        self.resets += 1
        self.step = 5


class TestRollback:
    def _exc(self, suspect=1):
        return exceptions.CollectiveIntegrityError(
            "boom", bucket="fused[8]", suspect_rank=suspect)

    def test_prefers_checkpoint_cut(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HOROVOD_ROLLBACK_BUDGET", "2")
        st = _FakeState(ckpt_dir=str(tmp_path))
        assert rollback.handle_failure(st, self._exc()) == 3
        assert st.waited and st.loaded and st.resets == 0
        assert st.step == 3
        assert rollback.replays() == 1

    def test_memory_snapshot_fallback(self):
        st = _FakeState(ckpt_dir="")
        assert rollback.handle_failure(st, self._exc()) == 5
        assert st.resets == 1 and not st.loaded

    def test_budget_exhaustion_reraises(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_ROLLBACK_BUDGET", "1")
        st = _FakeState()
        rollback.handle_failure(st, self._exc())
        with pytest.raises(exceptions.CollectiveIntegrityError):
            rollback.handle_failure(st, self._exc())
        assert rollback.replays() == 1  # the refused replay is not counted

    def test_quarantine_gating(self, monkeypatch):
        from horovod_tpu.elastic import fault_inject

        monkeypatch.setattr(fault_inject, "_initial_rank", 1)
        assert not rollback.should_quarantine(self._exc(suspect=1))  # off
        monkeypatch.setenv("HOROVOD_INTEGRITY_QUARANTINE", "1")
        assert rollback.should_quarantine(self._exc(suspect=1))
        assert not rollback.should_quarantine(self._exc(suspect=0))
        assert not rollback.should_quarantine(self._exc(suspect=None))

    def test_memory_rollback_restores_bit_identical(self, monkeypatch):
        """ArrayState memory-snapshot path: after a poisoned update the
        rollback restores the exact committed bytes."""
        from horovod_tpu.elastic.state import ArrayState

        golden = np.arange(4, dtype=np.float32) * 0.1
        st = ArrayState(params={"w": golden.copy()}, optimizer=None, step=3)
        st.params["w"] = st.params["w"] * np.float32(np.nan)
        st.step = 9
        assert rollback.handle_failure(st, self._exc()) == 3
        assert st.step == 3
        np.testing.assert_array_equal(np.asarray(st.params["w"]), golden)


# ---------------------------------------------------------------------------
# data-plane digests (single-controller, 8 virtual devices)
# ---------------------------------------------------------------------------

class TestDataPlaneDigests:
    @pytest.mark.parametrize("op", ["sum", "avg", "min", "max"])
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int32"])
    def test_fused_digest_agrees_on_clean_payloads(
            self, hvd, integrity_on, op, dtype):
        """Every reduce op × dtype passes the in-band digest with no
        false positive — in particular min/max, whose fused-bucket
        padding is the ±inf reduce identity and must be masked out of
        the non-finite count."""
        red = {"sum": hvd.Sum, "avg": hvd.Average,
               "min": hvd.Min, "max": hvd.Max}[op]
        reducer = {"sum": np.sum, "avg": np.mean,
                   "min": np.min, "max": np.max}[op]
        vals = [np.full((5,), i + 1).astype(dtype)
                for i in range(hvd.size())]
        h = hvd.allreduce_async(hvd.stack_per_worker(vals),
                                name=f"dig/{op}/{dtype}", op=red)
        out = np.asarray(hvd.synchronize(h)).astype(np.float64)
        np.testing.assert_allclose(
            out, reducer(np.stack(vals).astype(np.float64), axis=0),
            rtol=1e-2 if dtype == "bfloat16" else 1e-6)

    def test_fused_digest_deterministic_bf16(self, hvd, integrity_on):
        """Same bf16 payload twice → bit-identical reduced bytes, so
        identical digests on every replica (the agreement vote relies on
        reduction-order determinism)."""
        rng = np.random.RandomState(7)
        vals = [jnp.asarray(rng.randn(33).astype(np.float32), jnp.bfloat16)
                for _ in range(hvd.size())]
        outs = []
        for trial in range(2):
            h = hvd.allreduce_async(hvd.stack_per_worker(vals),
                                    name=f"det/bf16/{trial}", op=hvd.Sum)
            outs.append(np.asarray(hvd.synchronize(h)).copy())
        assert digest.checksum(outs[0]) == digest.checksum(outs[1])

    def test_fused_nan_injection_names_row(self, hvd, integrity_on,
                                           monkeypatch):
        """The executor pack-path injection fires after one clean
        dispatch; the on-device digest names the poisoned row and the
        runtime survives the verdict."""
        monkeypatch.setenv("HOROVOD_FAULT_INJECT", "nan:5:after=1")
        inject.reset()

        def reduce_once(tag):
            h = hvd.allreduce_async(
                hvd.stack_per_worker(
                    [np.full((4,), float(i), np.float32)
                     for i in range(hvd.size())]),
                name=f"inj/{tag}")
            return hvd.synchronize(h)

        reduce_once("warm")  # countdown: not fired yet
        with pytest.raises(exceptions.NumericalError) as ei:
            reduce_once("hit")
        assert ei.value.suspect_rank == 5
        assert "fused" in (ei.value.bucket or "")
        # the failure was surfaced to the caller, not the cycle loop:
        # the next collective must succeed
        out = np.asarray(reduce_once("after"))
        np.testing.assert_allclose(
            out, np.full((4,), np.mean(np.arange(hvd.size()))))

    def test_eager_stacked_nan_names_rank(self, hvd, integrity_on):
        vals = [np.full((3,), 1.0, np.float32) for _ in range(hvd.size())]
        vals[3][1] = np.nan
        with pytest.raises(exceptions.NumericalError) as ei:
            hvd.allreduce(hvd.stack_per_worker(vals), name="g0")
        assert ei.value.suspect_rank == 3
        assert ei.value.tensor == "g0"

    def test_zero_sharded_digest_flags_nan_grad(self, hvd, integrity_on):
        import optax

        params = {"w": np.ones(16, np.float32)}
        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        grads = {"w": np.ones(16, np.float32)}
        upd, state = sh.update(grads, state, params)  # cadence hit, clean
        digest.reset()
        grads["w"][2] = np.nan
        with pytest.raises(exceptions.NumericalError) as ei:
            sh.update(grads, state, params)
        assert ei.value.bucket == "zero.grads"

    def test_disabled_by_default(self, hvd, monkeypatch):
        """HOROVOD_INTEGRITY off: a NaN flows through unchecked (the
        pre-PR-10 behavior is the default)."""
        monkeypatch.delenv("HOROVOD_INTEGRITY", raising=False)
        vals = [np.full((3,), 1.0, np.float32) for _ in range(hvd.size())]
        vals[0][0] = np.nan
        out = np.asarray(hvd.allreduce(hvd.stack_per_worker(vals),
                                       name="off"))
        assert np.isnan(out[0])


# ---------------------------------------------------------------------------
# metrics presence
# ---------------------------------------------------------------------------

def test_metric_families_registered():
    from horovod_tpu.metrics import registry

    snap = registry().snapshot()
    for fam in ("horovod_integrity_checks_total",
                "horovod_integrity_violations_total",
                "horovod_integrity_rollbacks_total",
                "horovod_integrity_skipped_steps_total"):
        assert fam in snap, sorted(snap)
