"""Numerical-integrity acceptance over the real socket transport
(ISSUE.md PR 10).

Fast (tier-1) cells prove the two halves of the integrity plane loop
end to end with real worker processes:

* a one-shot bit flip on rank 1's copy of the 5th allreduce result is
  detected by the per-dispatch digest exchange, every rank rolls back
  IN PLACE (generation stays 0 — no process restart, no re-form) to
  the last checkpoint and replays to the exact final weights;
* a one-shot NaN that reaches every rank's reduced gradient (digests
  off) is skipped in lockstep by the step-level spike guard, costing
  one retried step and nothing else.

The full scenario matrix (postmortem culprit attribution, manifest
verification) lives in tools/chaos_matrix.py; both integrity cells are
repeated from there slow-marked.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.runtime.native import native_built

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "chaos_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(world, extra_env, timeout=240):
    rendezvous = RendezvousServer(host="127.0.0.1")
    http_port = rendezvous.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "JAX_PLATFORMS": "cpu",
            })
            env.update(extra_env)
            procs.append(subprocess.Popen(
                [sys.executable, WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        rendezvous.stop()
    return procs, outs


def _result(out):
    for line in out.splitlines():
        if line.startswith("CHAOS_RESULT "):
            return json.loads(line[len("CHAOS_RESULT "):])
    raise AssertionError("no CHAOS_RESULT line in:\n" + out[-2000:])


def test_bitflip_digest_detects_and_rolls_back_in_place(tmp_path):
    """SDC on the wire: the digest vote fires, every rank restores the
    step-4 checkpoint without leaving its process, and the replay ends
    bit-identical to an uninjected run (w == 8.0 exactly)."""
    procs, outs = _launch(3, {
        "HOROVOD_FAULT_INJECT": "bitflip:1:after=4",
        "HOROVOD_INTEGRITY": "1",
        "HOROVOD_INTEGRITY_INTERVAL": "1",
        "HOROVOD_CKPT_DIR": str(tmp_path / "ckpts"),
        "HOROVOD_CKPT_ASYNC": "0",
        "HOROVOD_ELASTIC_MIN_WORKERS": "3",
    })
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out[-3000:])
        res = _result(out)
        assert res["step"] == 8, (i, res)
        assert res["w"] == 8.0, (i, res)  # bit-identical replay
        assert res["generation"] == 0, (i, res)  # no restart, no re-form
        assert res["integrity_violations"] >= 1, (i, res)
        assert res["rollbacks"] >= 1, (i, res)
        assert res["skipped_steps"] == 0, (i, res)


def test_nan_spike_guard_skips_step_in_lockstep():
    """Non-finite payload with digests off: the EWMA spike guard on the
    reduced gradient skips the poisoned step on every rank (nothing
    applied, nothing committed) and the retry converges exactly."""
    procs, outs = _launch(2, {
        "HOROVOD_FAULT_INJECT": "nan:1:after=4",
        "HOROVOD_INTEGRITY": "1",
        "HOROVOD_INTEGRITY_INTERVAL": "0",
        "CHAOS_INTEGRITY_GUARD": "1",
        "HOROVOD_ELASTIC_MIN_WORKERS": "2",
    }, timeout=180)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out[-3000:])
        res = _result(out)
        assert res["step"] == 8, (i, res)
        assert res["w"] == 8.0, (i, res)
        assert res["skipped_steps"] == 1, (i, res)
        assert res["rollbacks"] == 0, (i, res)


@pytest.mark.slow
@pytest.mark.parametrize("cell", ["integrity_bitflip_rollback",
                                  "integrity_nan_skipstep"])
def test_chaos_matrix_integrity_cells(cell):
    """Full matrix cells: adds manifest verification and the merged
    flight-recorder postmortem naming the flipped rank."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_matrix.py"),
         "--only", cell],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
