"""High-level Trainer tests (reference: test/test_keras.py — wrapped
optimizer trains, callbacks fire, save/load round-trips with optimizer
rewrap)."""

import numpy as np
import optax
import pytest

import horovod_tpu.keras as hvd_keras
from horovod_tpu.models.mnist import MnistConvNet


def _data(n=256):
    rng = np.random.RandomState(0)
    return (rng.rand(n, 28, 28, 1).astype(np.float32),
            rng.randint(0, 10, (n,)).astype(np.int32))


class TestTrainer:
    def test_fit_reduces_loss_and_history(self, hvd):
        images, labels = _data()
        trainer = hvd_keras.Trainer(MnistConvNet(), optax.adam(1e-3),
                                    input_shape=(1, 28, 28, 1))
        history = trainer.fit(images, labels, epochs=3, batch_size=8,
                              shuffle=False, verbose=0)
        assert len(history["loss"]) == 3
        assert history["loss"][-1] < history["loss"][0]

    def test_callbacks_fire_and_average(self, hvd):
        images, labels = _data(64)

        class Counter(hvd_keras.Callback):
            begins = ends = batches = 0

            def on_epoch_begin(self, epoch, state):
                Counter.begins += 1
                return state

            def on_batch_begin(self, batch, state):
                Counter.batches += 1
                return state

            def on_epoch_end(self, epoch, state, metrics=None):
                Counter.ends += 1
                return state, metrics

        trainer = hvd_keras.Trainer(MnistConvNet(), optax.adam(1e-3),
                                    input_shape=(1, 28, 28, 1))
        trainer.fit(images, labels, epochs=2, batch_size=8, verbose=0,
                    callbacks=[Counter(),
                               hvd_keras.MetricAverageCallback(),
                               hvd_keras.BroadcastGlobalVariablesCallback()])
        assert Counter.begins == 2 and Counter.ends == 2
        assert Counter.batches == 2 * (64 // (8 * hvd.size()))

    def test_save_load_roundtrip(self, hvd, tmp_path):
        images, labels = _data(64)
        trainer = hvd_keras.Trainer(MnistConvNet(), optax.adam(1e-3),
                                    input_shape=(1, 28, 28, 1))
        trainer.fit(images, labels, epochs=1, batch_size=8, verbose=0)
        d = str(tmp_path / "ckpts")
        trainer.save(d, step=1)

        # the reference's load_model: fresh optimizer gets rewrapped and
        # its state restored
        restored = hvd_keras.Trainer.load(d, MnistConvNet(),
                                          optax.adam(1e-3),
                                          input_shape=(1, 28, 28, 1))
        assert restored.state.step == 1
        for a, b in zip(_leaves(trainer.state.params),
                        _leaves(restored.state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        # training continues from restored state
        h = restored.fit(images, labels, epochs=2, initial_epoch=1,
                         batch_size=8, verbose=0)
        assert len(h["loss"]) == 1

    def test_evaluate_and_predict(self, hvd):
        images, labels = _data(32)
        trainer = hvd_keras.Trainer(MnistConvNet(), optax.adam(1e-3),
                                    input_shape=(1, 28, 28, 1))
        preds = trainer.predict(images)
        assert preds.shape == (32, 10)
        metrics = trainer.evaluate(images, labels)
        assert 0.0 <= metrics["accuracy"] <= 1.0
        assert metrics["loss"] > 0

    def test_too_small_dataset_raises(self, hvd):
        images, labels = _data(4)
        trainer = hvd_keras.Trainer(MnistConvNet(), optax.adam(1e-3),
                                    input_shape=(1, 28, 28, 1))
        with pytest.raises(ValueError, match="smaller than one"):
            trainer.fit(images, labels, batch_size=64)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


class TestLrCallbacks:
    def test_warmup_callback_drives_injected_lr(self, hvd):
        import jax
        import optax

        images, labels = _data(64)
        opt = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
        trainer = hvd_keras.Trainer(MnistConvNet(), opt,
                                    input_shape=(1, 28, 28, 1))
        warmup = hvd_keras.LearningRateWarmupCallback(
            base_lr=0.1, warmup_epochs=2.0, steps_per_epoch=2, size=4)
        trainer.fit(images, labels, epochs=1, batch_size=8, verbose=0,
                    callbacks=[warmup])

        def find_lr(tree):
            found = []
            jax.tree_util.tree_map(
                lambda n: found.append(float(n.hyperparams["learning_rate"]))
                if hasattr(n, "hyperparams") else None,
                tree, is_leaf=lambda n: hasattr(n, "hyperparams"))
            return found[0]

        lr = find_lr(trainer.state.opt_state)
        # warmup ramps from base 0.1 toward 0.4; after a few batches the
        # injected LR must have moved off the base value
        assert lr > 0.1

    def test_lr_callback_without_injection_raises(self, hvd):
        import optax
        import pytest as _pytest

        images, labels = _data(64)
        trainer = hvd_keras.Trainer(MnistConvNet(), optax.sgd(0.1),
                                    input_shape=(1, 28, 28, 1))
        warmup = hvd_keras.LearningRateWarmupCallback(
            base_lr=0.1, warmup_epochs=2.0, steps_per_epoch=2)
        with _pytest.raises(ValueError, match="inject_hyperparams"):
            trainer.fit(images, labels, epochs=1, batch_size=8, verbose=0,
                        callbacks=[warmup])


def test_accumulating_distributed_optimizer_not_double_wrapped(hvd):
    """DistributedOptimizer(backward_passes_per_step>1) must be detected
    as already-distributed (its update closure lives in dp.py)."""
    import optax
    from horovod_tpu.keras import _is_distributed

    opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                   backward_passes_per_step=2)
    assert _is_distributed(opt)
    assert not _is_distributed(optax.sgd(0.1))
