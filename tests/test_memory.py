"""Memory telemetry plane (ISSUE 13): the per-subsystem ledger, ownership
attribution, device reconciliation, OOM forensics through the executor
boundary, and the cross-rank postmortem report.

Tier-1 safe: the CPU backend reports no ``memory_stats()``, so device
truth comes from the ``jax.live_arrays()`` fallback — exactly the path
these tests exercise.
"""

import json
import os
import time

import numpy as np
import pytest

from horovod_tpu import flight_recorder, memory


class FakeXlaRuntimeError(Exception):
    pass


# the tracker routes on the type NAME (jaxlib's class is not importable
# on every backend), so a lookalike exercises the real branch
FakeXlaRuntimeError.__name__ = "XlaRuntimeError"

_OOM_MSG = ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "2147483648 bytes.")


@pytest.fixture
def tracker():
    """The process-wide tracker, state-restored after the test."""
    t = memory.tracker()
    t.stop()
    with t._lock:
        saved = (dict(t._claimed), dict(t._peaks), dict(t._providers),
                 list(t._samples), t._last_oom)
        t._claimed.clear()
        t._peaks.clear()
        t._samples.clear()
        t._last_oom = None
    was_enabled = t.enabled
    t.enabled = True
    yield t
    t.stop()
    with t._lock:
        t._claimed.clear()
        t._claimed.update(saved[0])
        t._peaks.clear()
        t._peaks.update(saved[1])
        t._providers.clear()
        t._providers.update(saved[2])
        t._samples.clear()
        t._samples.extend(saved[3])
        t._last_oom = saved[4]
    t.enabled = was_enabled


class TestLedger:
    def test_set_bytes_rolls_peaks(self, tracker):
        tracker.set_bytes("params", 1000)
        tracker.set_bytes("params", 400)
        led = tracker.ledger()
        assert led["subsystems"]["params"]["bytes"] == 400
        assert led["subsystems"]["params"]["peak_bytes"] == 1000

    def test_note_tree_bytes_is_shape_math(self, tracker):
        import jax.numpy as jnp

        tree = {"w": jnp.ones((8, 16), jnp.float32),
                "b": jnp.ones((16,), jnp.float32)}
        tracker.note_tree_bytes("grads", tree)
        led = tracker.ledger()
        assert led["subsystems"]["grads"]["bytes"] == (8 * 16 + 16) * 4

    def test_ledger_shape_and_builtin_pulls(self, tracker):
        led = tracker.ledger()
        for key in ("rank", "wall_time", "subsystems",
                    "total_claimed_bytes", "claimed_device_bytes",
                    "device", "reconcile_drift_ratio", "last_oom"):
            assert key in led
        # the built-in pulls always contribute host RSS (Linux CI)
        assert led["subsystems"]["host_rss"]["bytes"] > 0
        # host_rss is excluded from the device-claim total
        assert led["claimed_device_bytes"] <= led["total_claimed_bytes"]

    def test_registered_provider_is_polled_outside_lock(self, tracker):
        tracker.register("custom_pool", lambda: 12345)
        led = tracker.ledger()
        assert led["subsystems"]["custom_pool"]["bytes"] == 12345
        tracker.register("custom_pool", None)
        assert "custom_pool" not in tracker._providers

    def test_failing_provider_does_not_break_accounting(self, tracker):
        def boom():
            raise RuntimeError("subsystem mid-teardown")

        tracker.register("dying", boom)
        led = tracker.ledger()  # must not raise
        assert "host_rss" in led["subsystems"]
        tracker.register("dying", None)

    def test_disabled_tracker_skips_pushes(self, tracker):
        tracker.enabled = False
        tracker.set_bytes("params", 999)
        tracker.note_tree_bytes("grads", {"x": np.ones(4)})
        with tracker._lock:
            assert "params" not in tracker._claimed
            assert "grads" not in tracker._claimed

    def test_sampler_fills_the_ring(self, tracker):
        tracker.start(interval=0.02)
        deadline = time.monotonic() + 5.0
        while not tracker.samples() and time.monotonic() < deadline:
            time.sleep(0.02)
        tracker.stop()
        rows = tracker.samples()
        assert rows, "sampler produced no reconciliation samples"
        wall, claimed, actual = rows[0]
        assert wall > 0 and claimed >= 0 and actual >= 0


class TestOwnership:
    def test_adopt_and_owner_attribution(self, tracker):
        import jax.numpy as jnp

        arr = jnp.ones((64, 64), jnp.float32)
        tracker.adopt("params", {"w": arr})
        assert tracker.owner_of(arr) == "params"
        top = tracker.top_live_arrays(k=10 ** 6)
        mine = [r for r in top if r["shape"] == [64, 64]
                and r["owner"] == "params"]
        assert mine and mine[0]["bytes"] == 64 * 64 * 4
        assert mine[0]["dtype"] == "float32"

    def test_unadopted_arrays_are_unattributed(self, tracker):
        import jax.numpy as jnp

        arr = jnp.ones((3,), jnp.float32)
        assert tracker.owner_of(arr) is None


class TestOomDetection:
    def test_is_oom_matrix(self):
        assert memory.is_oom(FakeXlaRuntimeError(_OOM_MSG))
        assert memory.is_oom(FakeXlaRuntimeError("OOM when allocating"))
        assert not memory.is_oom(FakeXlaRuntimeError("INVALID_ARGUMENT"))
        assert memory.is_oom(MemoryError())
        assert memory.is_oom(ValueError("RESOURCE_EXHAUSTED: pool"))
        assert not memory.is_oom(ValueError("shape mismatch"))
        assert not memory.is_oom(None)

    def test_maybe_record_oom_is_selective(self, tracker):
        assert memory.maybe_record_oom(ValueError("benign"), "executor") \
            is False
        assert tracker.last_oom() is None

    def test_record_oom_forensics(self, tracker, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_DIR", str(tmp_path))
        monkeypatch.setattr(flight_recorder._recorder,
                            "_last_failure_dump", 0.0)
        flight_recorder.configure(rank=0)
        flight_recorder.set_state_provider("memory", tracker.ledger)
        try:
            tracker.set_bytes("grads", 5 * 10 ** 9)  # the dominant one
            tracker.set_bytes("params", 10 ** 9)
            assert memory.maybe_record_oom(
                FakeXlaRuntimeError(_OOM_MSG), where="executor") is True
            oom = tracker.last_oom()
            assert oom["where"] == "executor"
            assert oom["dominant_subsystem"] == "grads"
            assert isinstance(oom["top_live_arrays"], list)
            # the flight dump that followed embeds ledger + forensics
            dump = json.loads(
                (tmp_path / "flight-rank-0.json").read_text())
            mem = dump["state"]["memory"]
            assert mem["subsystems"]["grads"]["bytes"] == 5 * 10 ** 9
            assert mem["last_oom"]["dominant_subsystem"] == "grads"
            assert any(e["kind"] == "oom" for e in dump["events"])
        finally:
            flight_recorder.set_state_provider("memory", None)
            flight_recorder.configure(rank=0)

    def test_executor_boundary_records_oom(self, hvd, tracker, tmp_path,
                                           monkeypatch):
        """ISSUE 13 satellite: a RESOURCE_EXHAUSTED surfacing through
        ``_PendingOp.fail_exc`` leaves a flight dump whose memory state
        carries the ledger and the top-k live arrays."""
        from horovod_tpu.core import state
        from horovod_tpu.runtime import executor as ex_mod
        from horovod_tpu.runtime import types

        monkeypatch.setenv("HOROVOD_FLIGHT_RECORDER_DIR", str(tmp_path))
        monkeypatch.setattr(flight_recorder._recorder,
                            "_last_failure_dump", 0.0)
        flight_recorder.configure(rank=0)
        flight_recorder.set_state_provider("memory", tracker.ledger)
        try:
            tracker.set_bytes("params", 7 * 10 ** 9)
            ex = ex_mod.Executor(state.global_state().mesh)
            entry = types.TensorTableEntry(
                name="oom/x", tensor=np.ones((4,), "float32"))
            tok = ex_mod._PendingOp(ex, types.ALLREDUCE, [entry], None)
            tok.fail_exc(FakeXlaRuntimeError(_OOM_MSG))
            oom = tracker.last_oom()
            assert oom is not None and oom["where"] == "executor"
            assert oom["dominant_subsystem"] == "params"
            dump = json.loads(
                (tmp_path / "flight-rank-0.json").read_text())
            mem = dump["state"]["memory"]
            assert "subsystems" in mem and "last_oom" in mem
            assert isinstance(mem["last_oom"]["top_live_arrays"], list)
        finally:
            flight_recorder.set_state_provider("memory", None)
            flight_recorder.configure(rank=0)


def _mem_state(rank, subsystems, in_use, limit=0, oom=None):
    return {
        "rank": rank,
        "subsystems": {name: {"bytes": b, "peak_bytes": b}
                       for name, b in subsystems.items()},
        "claimed_device_bytes": sum(
            b for n, b in subsystems.items() if n != "host_rss"),
        "device": {"bytes_in_use": in_use, "peak_bytes_in_use": in_use,
                   "bytes_limit": limit, "live_array_bytes": in_use},
        "reconcile_drift_ratio": 0.01,
        "last_oom": oom,
    }


def _dump(rank, mem_state):
    return {"schema": flight_recorder.SCHEMA, "rank": rank,
            "launch_rank": rank, "pid": 1000 + rank,
            "host": "host%d" % rank, "reason": "test", "wall_time": 0.0,
            "clock_offset_seconds": 0.0, "dump_history": [], "events": [],
            "state": {"memory": mem_state}, "metrics": {}}


class TestPostmortemReport:
    def test_cross_rank_report(self):
        gib = 1024 ** 3
        dumps = [
            _dump(0, _mem_state(
                0, {"params": 4 * gib, "grads": 2 * gib,
                    "host_rss": gib}, in_use=7 * gib, limit=16 * gib)),
            _dump(1, _mem_state(
                1, {"params": 4 * gib, "grads": 9 * gib,
                    "host_rss": gib}, in_use=15 * gib, limit=16 * gib,
                oom={"where": "executor", "dominant_subsystem": "grads",
                     "top_live_arrays": [
                         {"bytes": 3 * gib, "shape": [1024, 786432],
                          "dtype": "float32", "owner": "grads"}]})),
        ]
        text = memory.format_memory_report(dumps)
        assert "=== memory report (2 ranks) ===" in text
        assert "rank 1: OOM at executor — dominant subsystem grads" in text
        assert "dominant subsystem: grads" in text
        assert "nearest HBM ceiling: rank 1" in text
        assert "93.8% full" in text
        assert "(grads)" in text  # the owner tag on the top live array

    def test_report_empty_without_memory_state(self):
        dumps = [_dump(0, None)]
        dumps[0]["state"] = {}
        assert memory.format_memory_report(dumps) == ""

    def test_format_postmortem_embeds_memory_section(self):
        dumps = [_dump(0, _mem_state(0, {"serve_kv": 2 ** 30},
                                     in_use=2 ** 30))]
        text = flight_recorder.format_postmortem(dumps)
        assert "=== memory report" in text
        assert "serve_kv" in text

    def test_postmortem_cli_names_dominant_subsystem(self, tmp_path,
                                                     capsys):
        """ISSUE 13 acceptance: ``tpurun --postmortem`` over dumps from
        an OOM-ing fleet names the dominant subsystem."""
        from horovod_tpu.run.run import run_commandline

        gib = 1024 ** 3
        for rank in range(2):
            mem_state = _mem_state(
                rank, {"optimizer_shards": (6 + rank) * gib},
                in_use=(7 + rank) * gib, limit=16 * gib,
                oom=({"where": "elastic",
                      "dominant_subsystem": "optimizer_shards",
                      "top_live_arrays": []} if rank == 1 else None))
            (tmp_path / ("flight-rank-%d.json" % rank)).write_text(
                json.dumps(_dump(rank, mem_state)))
        assert run_commandline(["--postmortem", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "dominant subsystem: optimizer_shards" in out
        assert "nearest HBM ceiling: rank 1" in out


class TestConfigure:
    def test_knobs_and_provider_registration(self, tracker, monkeypatch):
        monkeypatch.setenv("HOROVOD_MEMORY", "1")
        monkeypatch.setenv("HOROVOD_MEMORY_SAMPLE_SECONDS", "99")
        monkeypatch.setenv("HOROVOD_MEMORY_TOPK", "3")
        memory.configure(rank=5)
        try:
            assert tracker.enabled is True
            assert tracker.rank == 5
            assert tracker.sample_seconds == 99.0
            assert tracker.topk == 3
            assert "memory" in flight_recorder._recorder._providers
        finally:
            tracker.stop()
        monkeypatch.setenv("HOROVOD_MEMORY", "0")
        memory.configure(rank=5)
        assert tracker.enabled is False
        assert "memory" not in flight_recorder._recorder._providers

    def test_memory_state_document(self, tracker):
        tracker.set_bytes("params", 123)
        state = memory.memory_state()
        assert state["subsystems"]["params"]["bytes"] == 123
        assert isinstance(state["top_live_arrays"], list)
        assert isinstance(state["samples"], list)
        assert state["sample_seconds"] == tracker.sample_seconds


class TestMetricsRoute:
    def test_get_memory_route(self, tracker):
        """The metrics server serves the ledger at GET /memory."""
        import urllib.request

        from horovod_tpu.metrics import MetricsRegistry

        tracker.set_bytes("params", 4321)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/memory" % port, timeout=5) as r:
                doc = json.loads(r.read().decode())
            assert doc["subsystems"]["params"]["bytes"] == 4321
            assert "device" in doc and "samples" in doc
        finally:
            reg.stop_server()


class TestHvdTop:
    def test_render_against_live_endpoint(self, tracker):
        import sys

        from horovod_tpu.metrics import MetricsRegistry

        repo_tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools")
        if repo_tools not in sys.path:
            sys.path.insert(0, repo_tools)
        import hvd_top

        tracker.set_bytes("params", 2 ** 20)
        reg = MetricsRegistry()
        port = reg.serve(0)
        try:
            table = hvd_top.render(["127.0.0.1:%d" % port])
            assert "params" in table.splitlines()[0]
            assert "1.0M" in table
        finally:
            reg.stop_server()

    def test_render_unreachable_endpoint(self):
        import sys

        repo_tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools")
        if repo_tools not in sys.path:
            sys.path.insert(0, repo_tools)
        import hvd_top

        table = hvd_top.render(["127.0.0.1:1"])  # nothing listens there
        assert "unreachable" in table
