"""Runtime metrics subsystem tests: registry semantics, Prometheus
exposition, the HTTP endpoint, runtime instrumentation driven by real
collectives on the CPU mesh, per-rank dumps and the cross-rank
``tpurun --metrics-summary`` aggregation."""

import json
import urllib.request

import numpy as np
import pytest

from horovod_tpu.metrics import (COUNT_BUCKETS, MetricsRegistry,
                                 flatten_snapshot, format_summary, registry,
                                 summarize_dumps)


def _scalar(snap, name):
    """Unlabeled counter/gauge value from a snapshot, 0 if absent."""
    fam = snap.get(name)
    if not fam or not fam["values"]:
        return 0
    return fam["values"][0]["value"]


def _hist(snap, name, label=None):
    """Histogram child dict {count, sum, buckets}, empty if absent."""
    fam = snap.get(name)
    if not fam:
        return {"count": 0, "sum": 0.0, "buckets": []}
    for entry in fam["values"]:
        if label is None or label.items() <= entry["labels"].items():
            return entry["value"]
    return {"count": 0, "sum": 0.0, "buckets": []}


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "a counter")
        c.inc()
        c.inc(4)
        g = reg.gauge("g", "a gauge")
        g.set(7)
        g.inc(2)
        g.dec()
        h = reg.histogram("h_seconds", "a histogram", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        snap = reg.snapshot()
        assert _scalar(snap, "c_total") == 5
        assert _scalar(snap, "g") == 8
        hist = _hist(snap, "h_seconds")
        assert hist["count"] == 3 and hist["sum"] == 55.5
        # cumulative le buckets, +Inf last
        assert hist["buckets"] == [[1.0, 1], [10.0, 2], ["+Inf", 3]]
        # snapshot must be JSON-serializable end to end
        json.dumps(snap)

    def test_creation_is_idempotent_by_name(self):
        reg = MetricsRegistry()
        a = reg.counter("same_total")
        b = reg.counter("same_total")
        assert a is b
        a.inc()
        assert b.value == 1

    def test_labeled_children(self):
        reg = MetricsRegistry()
        c = reg.counter("ops_total", "per-op", labelnames=("op",))
        c.labels(op="ALLREDUCE").inc(3)
        c.labels(op="BROADCAST").inc()
        snap = reg.snapshot()
        vals = {tuple(e["labels"].items()): e["value"]
                for e in snap["ops_total"]["values"]}
        assert vals[(("op", "ALLREDUCE"),)] == 3
        assert vals[(("op", "BROADCAST"),)] == 1

    def test_histogram_le_boundary_is_inclusive(self):
        reg = MetricsRegistry()
        h = reg.histogram("b", buckets=COUNT_BUCKETS)
        h.observe(8.0)  # v == bound -> that bucket, not the next
        snap = _hist(reg.snapshot(), "b")
        by_bound = dict((str(b), c) for b, c in snap["buckets"])
        assert by_bound["8.0"] == 1 and by_bound["4.0"] == 0

    def test_prometheus_text_format(self):
        reg = MetricsRegistry()
        reg.counter("c_total", 'help with "quotes"').inc(2)
        reg.histogram("h", "lat", buckets=(0.5,),
                      labelnames=("op",)).labels(op='a"b').observe(0.1)
        text = reg.prometheus_text()
        assert "# TYPE c_total counter" in text
        assert "c_total 2" in text
        assert "# TYPE h histogram" in text
        assert 'h_bucket{op="a\\"b",le="0.5"} 1' in text
        assert 'h_bucket{op="a\\"b",le="+Inf"} 1' in text
        assert 'h_count{op="a\\"b"} 1' in text
        assert text.endswith("\n")


class TestHttpEndpoint:
    def test_serve_and_stop(self):
        reg = MetricsRegistry()
        reg.counter("up_total", "liveness").inc()
        port = reg.serve(0)  # ephemeral
        try:
            assert reg.http_port == port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                body = resp.read().decode()
            assert "up_total 1" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5)
        finally:
            reg.stop_server()
        assert reg.http_port is None

    def test_no_socket_when_env_unset(self, hvd_flat):
        # HOROVOD_METRICS_PORT unset -> init() must not create the
        # endpoint (zero idle cost)
        assert registry().http_port is None

    def test_init_starts_endpoint_from_env(self, tmp_path, monkeypatch):
        import horovod_tpu as hvd

        hvd.shutdown()
        monkeypatch.setenv("HOROVOD_METRICS_PORT", "0")
        hvd.init(mesh_shape=(1, 8))
        try:
            port = registry().http_port
            assert port is not None and port > 0
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
                assert b"horovod_" in resp.read()
        finally:
            hvd.shutdown()
        assert registry().http_port is None  # shutdown() stops it


class TestRuntimeInstrumentation:
    def test_collectives_move_the_metrics(self, hvd):
        """Real named collectives through the background cycle must move
        cycle timing, queue, cache, fusion, executor and handle-wait
        metrics (the acceptance path for the whole subsystem)."""
        before = hvd.metrics()

        def round_trip():
            vals = [np.full((8,), r, "float32") for r in range(hvd.size())]
            h = hvd.allreduce_async(hvd.stack_per_worker(vals),
                                    average=False, name="metrics.grad")
            out = hvd.synchronize(h)
            np.testing.assert_allclose(
                np.asarray(out), np.sum(np.stack(vals), 0))

        round_trip()
        round_trip()  # second negotiation of the same name: cache hit
        after = hvd.metrics()

        def delta(name):
            return _scalar(after, name) - _scalar(before, name)

        assert delta("horovod_cycles_total") >= 2
        assert delta("horovod_tensor_queue_enqueued_total") == 2
        assert delta("horovod_response_cache_misses_total") >= 1
        assert delta("horovod_response_cache_hits_total") >= 1
        # 2 rounds x one (8,) float32 per-worker tensor
        assert delta("horovod_fusion_bytes_total") == 2 * 8 * 4
        assert _scalar(after, "horovod_tensor_queue_depth") == 0

        cyc = _hist(after, "horovod_cycle_duration_seconds")
        assert cyc["count"] >= 2 and cyc["sum"] > 0
        tens = (_hist(after, "horovod_cycle_tensors")["count"]
                - _hist(before, "horovod_cycle_tensors")["count"])
        assert tens >= 2
        wait = (_hist(after, "horovod_handle_wait_seconds")["count"]
                - _hist(before, "horovod_handle_wait_seconds")["count"])
        assert wait == 2

        lat = _hist(after, "horovod_executor_op_duration_seconds",
                    label={"op": "ALLREDUCE"})
        assert lat["count"] >= 2

        def op_bytes(snap):
            fam = snap.get("horovod_executor_op_bytes_total", {})
            return sum(e["value"] for e in fam.get("values", [])
                       if e["labels"].get("op") == "ALLREDUCE")

        assert op_bytes(after) - op_bytes(before) >= 2 * 8 * 4

    def test_fusion_batch_metrics(self):
        """Multi-tensor bins are counted with their utilization at the
        unit level (the integration path fuses one tensor per cycle)."""
        from horovod_tpu.runtime import fusion
        from horovod_tpu.runtime import message as msg
        from horovod_tpu.runtime import types

        before = registry().snapshot()
        reqs = {
            n: msg.Request(0, types.ALLREDUCE, n, "float32", (16,),
                           reduce_op=types.REDUCE_SUM)
            for n in ("fa", "fb")
        }
        responses = [msg.Response(types.ALLREDUCE, ["fa"]),
                     msg.Response(types.ALLREDUCE, ["fb"])]
        fused = fusion.fuse_responses(responses, reqs,
                                      threshold_bytes=1 << 20)
        assert len(fused) == 1 and len(fused[0].tensor_names) == 2
        after = registry().snapshot()
        assert (_scalar(after, "horovod_fusion_batches_total")
                - _scalar(before, "horovod_fusion_batches_total")) == 1
        assert (_scalar(after, "horovod_fusion_tensors_total")
                - _scalar(before, "horovod_fusion_tensors_total")) == 2
        util = _hist(after, "horovod_fusion_buffer_utilization_ratio")
        assert util["count"] >= 1

    def test_timeline_counter_overlay(self, tmp_path, monkeypatch):
        """With HOROVOD_TIMELINE active the runtime emits Chrome "C"
        counter events each cycle, in the same trace as the per-tensor
        bars."""
        import horovod_tpu as hvd

        hvd.shutdown()
        path = str(tmp_path / "trace.json")
        monkeypatch.setenv("HOROVOD_TIMELINE", path)
        hvd.init(mesh_shape=(1, 8))
        try:
            h = hvd.allreduce_async(
                hvd.stack_per_worker(
                    [np.ones((4,), "float32")] * hvd.size()),
                average=False, name="overlay.grad")
            hvd.synchronize(h)
        finally:
            hvd.shutdown()
        events = json.load(open(path))
        counters = [e for e in events if e.get("ph") == "C"]
        names = {e["name"] for e in counters}
        assert {"queue_depth", "cache_hits", "cache_misses",
                "fusion_bytes", "cycles"} <= names
        assert all("value" in e["args"] for e in counters)
        # same epoch-microsecond clock domain as the per-tensor events
        b_ts = [e["ts"] for e in events if e.get("ph") == "B"]
        assert b_ts and counters[0]["ts"] > 0

    def test_stall_metrics_and_arrival_baseline(self):
        """The stall age baseline is the request's arrival in the message
        table, so a warning fires on the first scan past warning_time —
        not one full interval later — and warnings/shutdowns count."""
        import time as _time

        from horovod_tpu.runtime import message as msg
        from horovod_tpu.runtime import types
        from horovod_tpu.runtime.controller import MessageTable
        from horovod_tpu.stall import StallInspector

        before = registry().snapshot()
        table = MessageTable()
        table.increment(
            msg.Request(0, types.ALLREDUCE, "stalled", "float32", (1,)),
            world=2)
        t_arrival = table.first_request_time("stalled")
        assert t_arrival is not None

        insp = StallInspector(warning_time_seconds=0.05,
                              shutdown_time_seconds=0.1)
        _time.sleep(0.12)
        # single scan, age measured from arrival: already past BOTH
        # thresholds (the old first-scan baseline would report age 0 here)
        assert insp.check(table, world=2) is True
        after = registry().snapshot()
        assert (_scalar(after, "horovod_stall_warnings_total")
                - _scalar(before, "horovod_stall_warnings_total")) == 1
        assert (_scalar(after, "horovod_stall_shutdowns_total")
                - _scalar(before, "horovod_stall_shutdowns_total")) == 1
        # pop clears the arrival stamp
        table.pop("stalled")
        assert table.first_request_time("stalled") is None


class TestDumpAndSummary:
    def _write_dump(self, path, rank, cycles, wait_sum, wait_count):
        reg = MetricsRegistry()
        reg.counter("horovod_cycles_total").inc(cycles)
        h = reg.histogram("horovod_handle_wait_seconds", buckets=(1.0,))
        for _ in range(wait_count):
            h.observe(wait_sum / wait_count)
        with open(path, "w") as f:
            json.dump({"rank": rank, "metrics": reg.snapshot()}, f)

    def test_summarize_dumps_min_median_max(self, tmp_path):
        paths = []
        for rank, cycles in enumerate((10, 30, 20)):
            p = str(tmp_path / f"metrics-rank-{rank}.json")
            self._write_dump(p, rank, cycles, wait_sum=cycles / 10.0,
                             wait_count=2)
            paths.append(p)
        rows = dict((r[0], r[1:]) for r in summarize_dumps(paths))
        assert rows["horovod_cycles_total"] == (10, 20, 30)
        lo, mid, hi = rows["horovod_handle_wait_seconds.mean"]
        assert (lo, mid, hi) == (0.5, 1.0, 1.5)
        text = format_summary(summarize_dumps(paths), n_ranks=3)
        assert text.splitlines()[0] == "cross-rank metrics summary (3 ranks)"
        assert "metric" in text and "median" in text

    def test_tpurun_metrics_summary_cli(self, tmp_path, capsys):
        from horovod_tpu.run.run import run_commandline

        p0 = str(tmp_path / "m0.json")
        p1 = str(tmp_path / "m1.json")
        self._write_dump(p0, 0, cycles=5, wait_sum=1.0, wait_count=1)
        self._write_dump(p1, 1, cycles=9, wait_sum=3.0, wait_count=1)
        rc = run_commandline(["--metrics-summary", p0, p1])
        assert rc == 0
        out = capsys.readouterr().out
        assert "cross-rank metrics summary (2 ranks)" in out
        assert "horovod_cycles_total" in out
        line = [ln for ln in out.splitlines()
                if ln.startswith("horovod_cycles_total")][0]
        assert line.split()[1:] == ["5", "7", "9"]

    def test_cli_errors(self, tmp_path, capsys):
        from horovod_tpu.run.run import run_commandline

        assert run_commandline(["--metrics-summary"]) == 2
        bad = str(tmp_path / "nope.json")
        assert run_commandline(["--metrics-summary", bad]) == 2

    def test_registry_dump_layouts(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("x_total").inc()
        # directory layout
        d = str(tmp_path / "dumps")
        out = reg.dump(d, rank=3)
        assert out.endswith("metrics-rank-3.json")
        # {rank} placeholder
        out2 = reg.dump(str(tmp_path / "m-{rank}.json"), rank=1)
        assert out2.endswith("m-1.json")
        data = json.load(open(out2))
        assert data["rank"] == 1
        assert data["metrics"]["x_total"]["values"][0]["value"] == 1

    def test_shutdown_writes_dump(self, tmp_path, monkeypatch):
        import horovod_tpu as hvd

        hvd.shutdown()
        d = str(tmp_path / "dumps")
        monkeypatch.setenv("HOROVOD_METRICS_DUMP", d)
        hvd.init(mesh_shape=(1, 8))
        hvd.shutdown()
        data = json.load(open(f"{d}/metrics-rank-0.json"))
        assert "metrics" in data and data["rank"] == 0
