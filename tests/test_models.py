"""Model + training-step tests, including the graft entry contract."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


class TestResNet:
    def test_resnet18_forward_shape(self, hvd_flat):
        from horovod_tpu.models.resnet import ResNet18

        model = ResNet18(num_classes=10, dtype=jnp.float32)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        out = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_resnet50_param_count(self, hvd_flat):
        from horovod_tpu.models.resnet import ResNet50

        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 64, 64, 3)), train=False)
        n_params = sum(x.size for x in
                       jax.tree_util.tree_leaves(variables["params"]))
        # canonical ResNet-50 ImageNet size: ~25.5M params
        assert 25_000_000 < n_params < 26_000_000

    def test_space_to_depth_conv_init_is_exact(self, hvd_flat):
        """The MXU-friendly input-conv reparametrization must compute
        the SAME function as the direct 7x7/2 conv on the same
        (7,7,3,64) parameter — checkpoint-interchangeable by
        construction (tools/conv0_s2d.py measures the 1.43x layer
        speedup on chip)."""
        from horovod_tpu.models.resnet import ResNet50

        x = jnp.asarray(np.random.RandomState(0).uniform(
            -1, 1, (2, 64, 64, 3)), jnp.float32)
        s2d = ResNet50(num_classes=10, dtype=jnp.float32)
        direct = ResNet50(num_classes=10, dtype=jnp.float32,
                          space_to_depth=False)
        variables = s2d.init(jax.random.PRNGKey(0), x[:1], train=False)
        # identical param trees (same names/shapes) serve both models
        out_a = s2d.apply(variables, x, train=False)
        out_b = direct.apply(variables, x, train=False)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                                   rtol=1e-5, atol=1e-5)


class TestTrainStep:
    def test_mnist_train_step_runs_and_learns(self, hvd):
        from horovod_tpu.models.mnist import MnistConvNet
        from horovod_tpu import training

        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        state = training.create_train_state(model, opt, (1, 28, 28, 1))
        step, batch_sharding = training.make_train_step(model, opt)

        rng = np.random.RandomState(0)
        images = jax.device_put(
            rng.rand(16, 28, 28, 1).astype(np.float32), batch_sharding)
        labels = jax.device_put(
            rng.randint(0, 10, (16,)).astype(np.int32), batch_sharding)

        params, stats, opt_state = (state.params, state.batch_stats,
                                    state.opt_state)
        losses = []
        for _ in range(10):
            loss, params, stats, opt_state = step(params, stats, opt_state,
                                                  images, labels)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # memorizing a fixed batch


class TestGraftEntry:
    def test_entry_compiles(self, hvd_flat):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 1000)

    def test_dryrun_multichip(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)


class TestTransformer:
    def _tiny(self, causal, **kw):
        from horovod_tpu.models.transformer import Transformer

        return Transformer(vocab_size=64, d_model=32, num_layers=2,
                           num_heads=2, d_ff=64, max_seq=64, causal=causal,
                           dtype=jnp.float32, **kw)

    def test_bert_forward_shape(self, hvd_flat):
        model = self._tiny(causal=False)
        tokens = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
        out = model.apply(variables, tokens, train=False)
        assert out.shape == (2, 16, 64)
        assert out.dtype == jnp.float32

    def test_causal_masking_matters(self, hvd_flat):
        """A causal decoder's logits at position t must not depend on
        tokens after t; a bidirectional encoder's do."""
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 64, (1, 16)), jnp.int32)
        tokens2 = tokens.at[0, -1].set((int(tokens[0, -1]) + 1) % 64)

        gpt = self._tiny(causal=True)
        variables = gpt.init(jax.random.PRNGKey(1), tokens, train=False)
        a = gpt.apply(variables, tokens, train=False)
        b = gpt.apply(variables, tokens2, train=False)
        np.testing.assert_allclose(a[0, :-1], b[0, :-1], atol=1e-5)

        bert = self._tiny(causal=False)
        variables = bert.init(jax.random.PRNGKey(1), tokens, train=False)
        a = bert.apply(variables, tokens, train=False)
        b = bert.apply(variables, tokens2, train=False)
        assert np.abs(np.asarray(a[0, :-1]) - np.asarray(b[0, :-1])).max() > 1e-6

    def test_gpt_memorizes_batch(self, hvd):
        import optax
        from horovod_tpu import training
        from horovod_tpu.models.transformer import causal_lm_loss

        model = self._tiny(causal=True)
        opt = hvd.DistributedOptimizer(optax.adam(5e-3))
        state = training.create_train_state(
            model, opt, (1, 16), input_dtype=jnp.int32)
        step, batch_sharding = training.make_train_step(
            model, opt, loss_fn=lambda logits, labels: causal_lm_loss(
                logits, labels))

        rng = np.random.RandomState(0)
        tokens = jax.device_put(
            rng.randint(0, 64, (8, 16)).astype(np.int32), batch_sharding)

        params, stats, opt_state = (state.params, state.batch_stats,
                                    state.opt_state)
        losses = []
        for _ in range(15):
            loss, params, stats, opt_state = step(params, stats, opt_state,
                                                  tokens, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_gathered_mlm_loss_matches_full_logits(self, hvd_flat):
        """The gather-before-projection MLM path (output='hidden' +
        masked_lm_loss_gathered) must equal the full-logits
        masked_lm_loss exactly when the gathered positions are the mask
        — it is an algebraic rearrangement, not an approximation."""
        from horovod_tpu.models.transformer import (
            masked_lm_loss, masked_lm_loss_gathered)

        model = self._tiny(causal=False)
        rng = np.random.RandomState(3)
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens, train=False)

        m = 4
        positions = jnp.asarray(
            np.stack([np.sort(rng.choice(16, m, replace=False))
                      for _ in range(2)]).astype(np.int32))
        mask = np.zeros((2, 16), np.int32)
        for b in range(2):
            mask[b, np.asarray(positions)[b]] = 1

        logits = model.apply(variables, tokens, train=False)
        full = masked_lm_loss(logits, tokens, jnp.asarray(mask))

        hidden = model.apply(variables, tokens, train=False,
                             output="hidden")
        assert hidden.shape == (2, 16, 32)
        emb = variables["params"]["token_embed"]["embedding"]
        labels = jnp.take_along_axis(tokens, positions, axis=1)
        gathered = masked_lm_loss_gathered(hidden, emb, positions, labels)
        np.testing.assert_allclose(float(gathered), float(full),
                                   rtol=1e-6)

    def test_chunked_causal_loss_matches_full_logits(self, hvd_flat):
        """causal_lm_loss_chunked (projection inside the chunk loop, no
        full logits tensor) must equal causal_lm_loss on the same model
        — an algebraic rearrangement, not an approximation."""
        from horovod_tpu.models.transformer import (causal_lm_loss,
                                                    causal_lm_loss_chunked)

        model = self._tiny(causal=True)
        rng = np.random.RandomState(11)
        tokens = jnp.asarray(rng.randint(0, 64, (3, 16)), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens, train=False)

        full = causal_lm_loss(
            model.apply(variables, tokens, train=False), tokens)
        hidden = model.apply(variables, tokens, train=False,
                             output="hidden")
        emb = variables["params"]["token_embed"]["embedding"]
        for chunk in (4, 8, 16):
            chunked = causal_lm_loss_chunked(hidden, emb, tokens,
                                             chunk=chunk)
            np.testing.assert_allclose(float(chunked), float(full),
                                       rtol=1e-6)
        with pytest.raises(ValueError):
            causal_lm_loss_chunked(hidden, emb, tokens, chunk=5)

    def test_fused_qkv_matches_unfused(self, hvd_flat):
        """fused_qkv=True is the same function: stacking the unfused
        query/key/value kernels (and biases) into the fused 'qkv' param
        must reproduce the unfused model's logits exactly."""
        rng = np.random.RandomState(5)
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)), jnp.int32)

        unfused = self._tiny(causal=False)
        fused = self._tiny(causal=False, fused_qkv=True)
        uv = unfused.init(jax.random.PRNGKey(0), tokens, train=False)
        fv = fused.init(jax.random.PRNGKey(0), tokens, train=False)
        fparams = jax.tree_util.tree_map(np.asarray, fv)
        for lyr in ("layer_0", "layer_1"):
            at = uv["params"][lyr]["attention"]
            dst = fparams["params"][lyr]["attention"]["qkv"]
            dst["kernel"] = np.stack(
                [np.asarray(at[n]["kernel"]) for n in
                 ("query", "key", "value")], axis=1)  # (d, 3, h, hd)
            dst["bias"] = np.stack(
                [np.asarray(at[n]["bias"]) for n in
                 ("query", "key", "value")], axis=0)  # (3, h, hd)

        a = unfused.apply(uv, tokens, train=False)
        b = fused.apply(fparams, tokens, train=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5)

    def test_bert_large_param_count(self, hvd_flat):
        from horovod_tpu.models.transformer import BertLarge

        model = BertLarge(vocab_size=30522, max_seq=128)
        tokens = jnp.zeros((1, 8), jnp.int32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), tokens, train=False))
        n_params = sum(int(np.prod(x.shape)) for x in
                       jax.tree_util.tree_leaves(variables["params"]))
        # BERT-Large: ~334M params (here without the pooler/NSP head and
        # with a short learned-position table)
        assert 330_000_000 < n_params < 345_000_000

    def test_masked_lm_loss(self, hvd_flat):
        from horovod_tpu.models.transformer import masked_lm_loss

        logits = jnp.zeros((2, 4, 8))
        labels = jnp.zeros((2, 4), jnp.int32)
        mask = jnp.array([[1, 1, 0, 0], [0, 0, 0, 0]], jnp.int32)
        loss = masked_lm_loss(logits, labels, mask)
        np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-5)


class _TinyBnNet:
    """Conv+BatchNorm model so the scan carries non-empty batch_stats
    (the path bench.py's ResNet-50 relies on)."""

    def __new__(cls):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = True):
                x = nn.Conv(8, (3, 3))(x)
                x = nn.BatchNorm(use_running_average=not train)(x)
                x = nn.relu(x).mean(axis=(1, 2))
                return nn.Dense(10)(x)

        return Net()


class TestTrainRound:
    def test_scanned_round_matches_sequential_steps(self, hvd):
        """make_train_round(steps=3) == three make_train_step calls,
        including the BatchNorm running-stats carry."""
        import optax
        from horovod_tpu import training

        model = _TinyBnNet()
        opt = hvd.DistributedOptimizer(optax.sgd(0.05))
        state = training.create_train_state(model, opt, (1, 28, 28, 1))
        assert state.batch_stats  # non-empty stats actually carried
        step, sh = training.make_train_step(model, opt, donate=False)
        round_fn, _ = training.make_train_round(model, opt, steps=3,
                                                donate=False)

        rng = np.random.RandomState(0)
        images = jax.device_put(rng.rand(16, 28, 28, 1).astype(np.float32), sh)
        labels = jax.device_put(rng.randint(0, 10, (16,)).astype(np.int32), sh)

        p, st, os_ = state.params, state.batch_stats, state.opt_state
        for _ in range(3):
            loss_seq, p, st, os_ = step(p, st, os_, images, labels)

        loss_rnd, p2, st2, os2 = round_fn(state.params, state.batch_stats,
                                          state.opt_state, images, labels)
        np.testing.assert_allclose(float(loss_rnd), float(loss_seq), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves((p, st)),
                        jax.tree_util.tree_leaves((p2, st2))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


class TestVggInception:
    def test_vgg16_param_count(self, hvd_flat):
        from horovod_tpu.models.vgg import VGG16

        model = VGG16(num_classes=1000)
        tokens = jnp.zeros((1, 224, 224, 3))
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), tokens, train=False))
        n = sum(int(np.prod(x.shape)) for x in
                jax.tree_util.tree_leaves(variables["params"]))
        # canonical VGG-16 ImageNet size: ~138.4M params
        assert 137_000_000 < n < 140_000_000

    def test_vgg16_forward(self, hvd_flat):
        from horovod_tpu.models.vgg import VGG16

        model = VGG16(num_classes=10, dtype=jnp.float32)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10) and out.dtype == jnp.float32

    def test_inception_v3_param_count(self, hvd_flat):
        from horovod_tpu.models.inception import InceptionV3

        model = InceptionV3(num_classes=1000)
        x = jnp.zeros((1, 299, 299, 3))
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), x, train=False))
        n = sum(int(np.prod(x.shape)) for x in
                jax.tree_util.tree_leaves(variables["params"]))
        # canonical Inception-V3 (no aux head): ~23.8M params
        assert 22_000_000 < n < 25_000_000

    def test_inception_v3_trains(self, hvd):
        import optax
        from horovod_tpu import training
        from horovod_tpu.models.inception import InceptionV3

        model = InceptionV3(num_classes=10, dtype=jnp.float32)
        opt = hvd.DistributedOptimizer(optax.sgd(0.01))
        state = training.create_train_state(model, opt, (1, 128, 128, 3))
        step, sh = training.make_train_step(model, opt)
        rng = np.random.RandomState(0)
        images = jax.device_put(rng.rand(8, 128, 128, 3).astype(np.float32), sh)
        labels = jax.device_put(rng.randint(0, 10, (8,)).astype(np.int32), sh)
        loss, p, st, os_ = step(state.params, state.batch_stats,
                                state.opt_state, images, labels)
        loss2, *_ = step(p, st, os_, images, labels)
        assert float(loss2) < float(loss)


class TestFusedConvKernels:
    """Parity pins for the conv-net MFU campaign (ISSUE 12): the
    space-to-depth Inception stem and the fused BN+ReLU epilogue must
    compute the same function as the direct formulations they replace."""

    def test_space_to_depth_stem_matches_direct_conv(self, hvd_flat):
        from horovod_tpu.models.inception import SpaceToDepthStem

        x = jnp.asarray(np.random.RandomState(0).uniform(
            -1, 1, (2, 75, 75, 3)), jnp.float32)  # odd size, like 299
        stem = SpaceToDepthStem(32, jnp.float32)
        variables = stem.init(jax.random.PRNGKey(0), x)
        folded = stem.apply(variables, x)
        direct = jax.lax.conv_general_dilated(
            x, variables["params"]["kernel"], (2, 2), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert folded.shape == direct.shape == (2, 37, 37, 32)
        np.testing.assert_allclose(np.asarray(folded), np.asarray(direct),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_bn_act_matches_unfused(self, hvd_flat):
        import flax.linen as nn
        from horovod_tpu.ops.pallas.conv_bn_act import FusedBatchNormAct

        x = jnp.asarray(np.random.RandomState(1).uniform(
            -2, 2, (4, 9, 9, 16)), jnp.float32)
        fused = FusedBatchNormAct(momentum=0.9, epsilon=1e-3,
                                  dtype=jnp.float32)
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-3, dtype=jnp.float32,
                           param_dtype=jnp.float32)
        # identical variable names by construction: one init serves both
        variables = fused.init(jax.random.PRNGKey(0), x)
        out_f, mut_f = fused.apply(variables, x,
                                   mutable=["batch_stats"])
        out_r, mut_r = ref.apply(variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(out_f),
                                   np.asarray(nn.relu(out_r)),
                                   rtol=1e-5, atol=1e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(mut_f["batch_stats"][k]),
                np.asarray(mut_r["batch_stats"][k]), rtol=1e-5, atol=1e-6)

    def test_fused_bn_act_gradients_match(self, hvd_flat):
        import flax.linen as nn
        from horovod_tpu.ops.pallas.conv_bn_act import FusedBatchNormAct

        x = jnp.asarray(np.random.RandomState(2).uniform(
            -2, 2, (2, 7, 7, 8)), jnp.float32)
        fused = FusedBatchNormAct(momentum=0.9, epsilon=1e-3,
                                  dtype=jnp.float32)
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-3, dtype=jnp.float32,
                           param_dtype=jnp.float32)
        variables = fused.init(jax.random.PRNGKey(0), x)

        def loss_fused(params, x):
            out, _ = fused.apply(
                {"params": params,
                 "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"])
            return jnp.sum(out ** 2)

        def loss_ref(params, x):
            out, _ = ref.apply(
                {"params": params,
                 "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"])
            return jnp.sum(nn.relu(out) ** 2)

        gf = jax.grad(loss_fused, argnums=(0, 1))(variables["params"], x)
        gr = jax.grad(loss_ref, argnums=(0, 1))(variables["params"], x)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            gf, gr)
