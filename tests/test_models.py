"""Model + training-step tests, including the graft entry contract."""

import jax
import jax.numpy as jnp
import numpy as np
import optax


class TestResNet:
    def test_resnet18_forward_shape(self, hvd_flat):
        from horovod_tpu.models.resnet import ResNet18

        model = ResNet18(num_classes=10, dtype=jnp.float32)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)), train=False)
        out = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_resnet50_param_count(self, hvd_flat):
        from horovod_tpu.models.resnet import ResNet50

        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 64, 64, 3)), train=False)
        n_params = sum(x.size for x in
                       jax.tree_util.tree_leaves(variables["params"]))
        # canonical ResNet-50 ImageNet size: ~25.5M params
        assert 25_000_000 < n_params < 26_000_000


class TestTrainStep:
    def test_mnist_train_step_runs_and_learns(self, hvd):
        from horovod_tpu.models.mnist import MnistConvNet
        from horovod_tpu import training

        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        state = training.create_train_state(model, opt, (1, 28, 28, 1))
        step, batch_sharding = training.make_train_step(model, opt)

        rng = np.random.RandomState(0)
        images = jax.device_put(
            rng.rand(16, 28, 28, 1).astype(np.float32), batch_sharding)
        labels = jax.device_put(
            rng.randint(0, 10, (16,)).astype(np.int32), batch_sharding)

        params, stats, opt_state = (state.params, state.batch_stats,
                                    state.opt_state)
        losses = []
        for _ in range(10):
            loss, params, stats, opt_state = step(params, stats, opt_state,
                                                  images, labels)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # memorizing a fixed batch


class TestGraftEntry:
    def test_entry_compiles(self, hvd_flat):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 1000)

    def test_dryrun_multichip(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)
