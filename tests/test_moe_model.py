"""MoE language model: expert-parallel LM trains end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import moe

VOCAB, D, SEQ, HEADS = 64, 32, 16, 4


def _params(hvd, rng):
    return moe.init_moe_lm(
        rng, vocab_size=VOCAB, d_model=D, num_layers=2, num_heads=HEADS,
        d_ff=64, n_experts=hvd.local_size(), max_seq=SEQ)


class TestMoeLm:
    def test_forward_shapes_and_aux(self, hvd_flat):
        rng = np.random.RandomState(0)
        params = _params(hvd_flat, rng)
        n = hvd_flat.local_size()
        tokens = jnp.asarray(rng.randint(0, VOCAB, (n * 2, SEQ)), jnp.int32)

        def inner(shared, experts, tokens):
            p = {"shared": shared, "experts": experts}
            logits, aux = moe.apply_moe_lm(p, tokens, "local", capacity=16,
                                           num_heads=HEADS)
            return logits, jax.lax.pmean(aux, "local")

        logits, aux = jax.jit(jax.shard_map(
            inner, mesh=hvd_flat.mesh(),
            in_specs=(P(), P("local"), P("local")),
            out_specs=(P("local"), P()), check_vma=False))(
            params["shared"], params["experts"], tokens)
        assert logits.shape == (n * 2, SEQ, VOCAB)
        assert float(aux) > 0.5  # balance loss near 1 at init

    def test_moe_lm_trains(self, hvd_flat):
        """LM loss decreases; expert and shared params both update."""
        rng = np.random.RandomState(1)
        params = _params(hvd_flat, rng)
        n = hvd_flat.local_size()
        tokens = jnp.asarray(rng.randint(0, VOCAB, (n * 2, SEQ)), jnp.int32)
        opt = optax.adam(3e-3)
        trainable = params
        state = opt.init(trainable)

        def loss_fn(trainable, tokens):
            def inner(shared, experts, tokens):
                p = {"shared": shared, "experts": experts}
                return moe.moe_lm_loss(p, tokens, "local", capacity=16,
                                       num_heads=HEADS)

            return jax.shard_map(
                inner, mesh=hvd_flat.mesh(),
                in_specs=(P(), P("local"), P("local")), out_specs=P(),
                check_vma=False)(trainable["shared"],
                                 trainable["experts"], tokens)

        @jax.jit
        def step(trainable, state, tokens):
            loss, g = jax.value_and_grad(loss_fn)(trainable, tokens)
            updates, state = opt.update(g, state, trainable)
            return loss, optax.apply_updates(trainable, updates), state

        first_experts = np.asarray(
            trainable["experts"]["layers"][0]["wi"]).copy()
        losses = []
        for _ in range(40):
            loss, trainable, state = step(trainable, state, tokens)
            losses.append(float(loss))
        assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])
        moved = np.abs(np.asarray(
            trainable["experts"]["layers"][0]["wi"]) - first_experts).max()
        assert moved > 1e-5  # experts actually trained
