"""Multi-process runtime integration tests.

The reference tests all native code through Python bindings under a real
multi-process launcher (reference: SURVEY.md §4 — ``mpirun -np 2`` /
horovodrun gloo). Here: spawn real worker processes wired together by the
launcher env contract (HOROVOD_RANK/SIZE + rendezvous address), each
driving the TCP SocketController + native ring data plane.
"""

import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.runtime.native import native_built

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(scenario: str, world: int, extra_env=None, timeout=90):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # workers don't need 8 fake devices
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_CONTROLLER": "socket",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, scenario],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytest.mark.parametrize("world", [2, 3])
def test_collectives_across_processes(world):
    procs, outs = _launch("collectives", world)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2, 3])
def test_dtype_matrix_across_processes(world):
    """Reference-breadth dtype x op sweep over the real wire (r5;
    reference: test/test_torch.py dtype sweeps, test_tensorflow.py
    fused many-small + variable-size allgather per dtype): 12 dtypes x
    allreduce(sum,min)/broadcast/variable-size allgather/reducescatter/
    alltoall, with 64-bit payloads that corrupt if anything narrows,
    plus a fused many-small burst across every dtype."""
    procs, outs = _launch("dtype_matrix", world, timeout=180)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2, 3])
def test_skewed_arrival_cycles(world):
    """Workers announcing the same tensor in different cycles — the
    scenario per-tensor negotiation exists for (uncached wait, deferred
    cache hits, synchronized invalidation on shape change)."""
    procs, outs = _launch("skewed_arrival", world, timeout=120)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


def test_shape_mismatch_errors_on_all_ranks():
    procs, outs = _launch("shape_mismatch", 2)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


@pytest.mark.parametrize("world", [2])
def test_tensorflow_binding_across_processes(world):
    """TF eager binding under a real multi-process world (reference:
    test/test_tensorflow.py under mpirun -np 2): collectives, custom
    gradients, DistributedGradientTape/Optimizer lockstep,
    broadcast_variables, IndexedSlices, object broadcast."""
    pytest.importorskip("tensorflow")
    procs, outs = _launch("tensorflow", world, timeout=300)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2, 3])
def test_tensorflow_error_paths_across_processes(world):
    """Mismatched shape/dtype THROUGH the TF binding raises on all ranks
    and the world stays usable (reference: test_tensorflow.py:314-460)."""
    pytest.importorskip("tensorflow")
    procs, outs = _launch("tensorflow_errors", world, timeout=300)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


def test_fusion_engages_through_bindings():
    """The fusion/dispatch win measured THROUGH the torch hook optimizer
    and the TF gradient tape, not just the raw named API (VERDICT r3 ask
    6): a 50-parameter model's step must cost a small handful of ring
    exchanges, not one negotiation per gradient."""
    pytest.importorskip("torch")
    pytest.importorskip("tensorflow")
    import json
    import subprocess

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "binding_fusion_bench.py")
    out = subprocess.run(
        [sys.executable, tool, "--np", "2"], capture_output=True,
        text=True, timeout=900, check=True)
    r = json.loads(out.stdout.strip().splitlines()[-1])
    for path in ("torch", "tf"):
        assert r[path]["fusion_dispatch_reduction_x"] >= 4, r[path]


@pytest.mark.parametrize("world", [2])
def test_tensorflow_graph_mode_across_processes(world):
    """TF1 graph-mode surface under a real multi-process world:
    BroadcastGlobalVariablesHook under MonitoredTrainingSession and the
    broadcast_variables graph op (reference:
    horovod/tensorflow/__init__.py:125-192)."""
    pytest.importorskip("tensorflow")
    procs, outs = _launch("tensorflow_graph", world, timeout=300)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2, 3])
def test_torch_binding_across_processes(world):
    """Torch DistributedOptimizer + broadcasts under a real multi-process
    world (reference: test/test_torch.py under mpirun -np 2)."""
    procs, outs = _launch("torch", world, timeout=150)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


def test_lane_hazard_watchdog_diagnoses_user_program_interleave():
    """Named op in flight + silent enqueue side (the caller 'busy in its
    own global program') must print the specific lane-hazard diagnostic
    within one stall-check period — the hazard _lane_check cannot
    intercept (VERDICT r2 ask 8)."""
    procs, outs = _launch(
        "lane_hazard", 2,
        extra_env={"HOROVOD_STALL_CHECK_TIME_SECONDS": "0.5"},
        timeout=120)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert any("interleaved in different orders across ranks" in out
               and "hazard/x" in out for out in outs), outs


def test_stall_triggers_global_shutdown():
    procs, outs = _launch(
        "stall_shutdown", 2,
        extra_env={
            "HOROVOD_STALL_CHECK_TIME_SECONDS": "0.5",
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "1",
        })
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


@pytest.mark.parametrize("world", [2, 3])
@pytest.mark.parametrize("engine", ["1", "0"])  # native / python cycle
def test_cache_churn_keeps_bits_aligned(world, engine):
    """Evictions (capacity 4 << 12 tensors) + periodic shape changes +
    skewed per-rank orders: cross-worker cache-bit alignment under churn,
    on both cycle engines."""
    procs, outs = _launch("cache_churn", world,
                          extra_env={"HOROVOD_CACHE_CAPACITY": "4",
                                     "HOROVOD_NATIVE_CYCLE": engine},
                          timeout=240)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


def test_peer_death_fails_survivors():
    """An abruptly killed rank must surface as an error on the survivors,
    not a hang (reference: launcher kills the job on any rank failure,
    gloo_run.py:256-262; pending callbacks get SHUT_DOWN_ERROR)."""
    procs, outs = _launch("peer_death", 2, timeout=120)
    assert procs[1].returncode == 17, outs[1]  # the planted death
    assert procs[0].returncode == 0, outs[0]   # survivor observed an error


@pytest.mark.parametrize("world", [2, 3])
def test_fusion_stress_mixed_tensors(world):
    """60 mixed-size/dtype named tensors per cycle, submitted in different
    orders per rank, across cache-warm rounds."""
    procs, outs = _launch("fusion_stress", world, timeout=150)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


def test_soak_combined_stress():
    """Multi-process soak: autotune + cache churn/invalidation + skewed
    arrival + torch hooks + eager interleave run SIMULTANEOUSLY for
    ~SOAK_SECONDS, then weights and cache bit maps are audited for
    cross-rank alignment (VERDICT r1 #8 — the ingredients' dedicated
    tests prove each alone; this proves composition). World defaults to
    4 because the CI box has ONE core — 8 fully-contended jax processes
    take >10 min of wall; set SOAK_WORLD=8 on real machines."""
    procs, outs = _launch(
        "soak", int(os.environ.get("SOAK_WORLD", "4")),
        extra_env={
            "HOROVOD_CACHE_CAPACITY": "3",
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "5",
            # 8 CPU-contended ranks: a loaded box can stall one rank's
            # cycle (autotune's block_until_ready) past the default 30s
            # verb timeout — raise it so only real hangs fail the soak
            "HOROVOD_GLOO_TIMEOUT_SECONDS": "150",
            "SOAK_SECONDS": os.environ.get("SOAK_SECONDS", "30"),
        },
        timeout=900)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "soak:" in out


@pytest.mark.parametrize("world", [1, 2, 4,
                                   pytest.param(8, marks=pytest.mark.slow)])
def test_zero_sharded_optimizer_parity(world):
    """ZeRO-1 sharded optimizer over the real wire at 1/2/4/8 ranks:
    reduce-scatter + shard update + allgather must reproduce the
    replicated update bit-exactly for SGD (integer-valued f32 grads,
    power-of-two worlds => exact ring math) and to f32 round-off for
    the fused flat AdamW. 8 ranks is slow-marked: one-core CI boxes
    serialize 8 jax processes (see test_soak_combined_stress)."""
    procs, outs = _launch("zero_parity", world, timeout=240)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2])
def test_debug_locks_witness_clean_run(world):
    """A short training loop under HOROVOD_DEBUG_LOCKS=1: the runtime's
    witness-wrapped locks must record zero violations, the observed
    acquisition order must be consistent with the static lock-order
    graph (hvd-analyze's claim holds at runtime), and lock_* events must
    reach the flight recorder (asserted in-worker, tests/mp_worker.py
    scenario debug_locks)."""
    procs, outs = _launch("debug_locks", world, timeout=180,
                          extra_env={"HOROVOD_DEBUG_LOCKS": "1"})
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK rank=" in out


@pytest.mark.parametrize("world", [2, 3])
def test_unnamed_eager_collectives_communicate(world):
    """Plain hvd.allreduce/allgather/broadcast (no name) in a
    multi-process world must exchange data, not silently return local
    values."""
    procs, outs = _launch("unnamed_eager", world)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


@pytest.mark.slow
def test_comms_degradation_alert_under_netdelay(tmp_path, capsys):
    """ISSUE 16 acceptance: a 150 ms netdelay window opening 3 s in must
    trip exactly one ``comms_degraded`` flight event per rank naming the
    host_ring lane (asserted in-worker, tests/mp_worker.py scenario
    comms_degraded), and the merged ``tpurun --postmortem`` over the
    shutdown dumps must render the cross-rank comms report."""
    flight_dir = tmp_path / "flight"
    # after=8 grants the workers' fast phase real headroom over a loaded
    # box's init tail; the worker anchors its own wake-up to its
    # scenario-entry stamp (an upper bound on chaos t0), so the window
    # is guaranteed open when the slow phase starts
    procs, outs = _launch(
        "comms_degraded", 2, timeout=180, extra_env={
            "HOROVOD_FAULT_INJECT": "netdelay:150:after=8",
            "COMMS_DELAY_AFTER": "8.5",
            "HOROVOD_FLIGHT_RECORDER_DIR": str(flight_dir),
        })
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "COMMS_DEGRADED_OK" in out
        assert "OK rank=" in out

    from horovod_tpu import flight_recorder
    dumps = flight_recorder.load_dumps(str(flight_dir))
    assert len(dumps) == 2
    for d in dumps:
        lanes = d["state"]["comms"]["lanes"]
        assert lanes["host_ring"]["degraded_count"] == 1, lanes

    from horovod_tpu.run.run import run_commandline
    assert run_commandline(["--postmortem", str(flight_dir)]) == 0
    out = capsys.readouterr().out
    assert "=== comms report (2 ranks) ===" in out
    assert "degraded host_ring allreduce" in out
    assert "slowest lane: host_ring" in out
