"""MXNet-shaped binding tests — ops, optimizer wrapper, broadcasts.

Mirrors the reference's mxnet binding semantics (reference:
test/test_mxnet.py + horovod/mxnet/__init__.py:40-125): ops accept
mutable numpy arrays — the binding is DELIBERATELY duck-typed (MXNet is
EOL and absent from the TPU stack; PARITY.md "Deliberate limits"), so
these tests witness the API contract on numpy, not an MXNet engine
integration. ``DistributedOptimizer`` folds the average into
``rescale_grad`` and allreduces with per-index names and priorities.

World model: single-controller 8-device mesh = 8 workers holding
replicated values (average is identity, sum multiplies by world size).
Priority *ordering* through the runtime is exercised in
test_runtime.py; here the hints are exercised through the public API.
"""

import numpy as np
import pytest

import horovod_tpu.mxnet as hvd

WORLD = 8


@pytest.fixture(autouse=True)
def _world():
    hvd.shutdown()
    hvd.init(mesh_shape=(1, WORLD))
    yield
    hvd.shutdown()


class TestOps:
    def test_allreduce_average_identity(self):
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        out = hvd.allreduce(x)
        assert isinstance(out, np.ndarray)
        assert out is not x
        np.testing.assert_allclose(out, x)

    def test_allreduce_sum(self):
        x = np.ones((3, 2), np.float32)
        out = hvd.allreduce(x, average=False, priority=5)
        np.testing.assert_allclose(out, x * WORLD)

    def test_allreduce_inplace_mutates(self):
        x = np.ones(4, np.float32)
        out = hvd.allreduce_(x, average=False)
        assert out is x
        np.testing.assert_allclose(x, np.full(4, WORLD, np.float32))

    def test_allreduce_inplace_rejects_immutable(self):
        with pytest.raises(TypeError):
            hvd.allreduce_([1.0, 2.0])

    def test_allgather(self):
        x = np.arange(4, dtype=np.float32).reshape(2, 2)
        out = hvd.allgather(x)
        assert out.shape == (2 * WORLD, 2)
        np.testing.assert_allclose(out[:2], x)

    def test_broadcast_out_of_place(self):
        x = np.arange(5, dtype=np.float32)
        out = hvd.broadcast(x, root_rank=0)
        assert out is not x
        np.testing.assert_allclose(out, x)

    def test_broadcast_inplace(self):
        x = np.arange(5, dtype=np.float32)
        out = hvd.broadcast_(x, root_rank=0, name="bp")
        assert out is x

    def test_broadcast_bad_root(self):
        with pytest.raises(ValueError):
            hvd.broadcast(np.ones(2, np.float32), root_rank=WORLD + 3)

    def test_dtypes(self):
        for dtype in [np.float32, np.float64, np.float16, np.int32,
                      np.int64, np.uint8]:
            x = np.ones(5, dtype=dtype)
            out = hvd.allreduce(x, average=False)
            assert out.dtype == dtype, dtype
            np.testing.assert_array_equal(out, x * WORLD)


class _FakeSGD:
    """Minimal MXNet-optimizer-protocol object (rescale_grad + update)."""

    def __init__(self, lr=0.1, rescale_grad=1.0):
        self.lr = lr
        self.rescale_grad = rescale_grad
        self.updates = []

    def update(self, index, weight, grad, state):
        self.updates.append(index)
        if isinstance(index, (tuple, list)):
            # real MXNet optimizers accept list indices (mx.optimizer
            # .Optimizer.update's multi-index form)
            for w, g in zip(weight, grad):
                w -= self.lr * self.rescale_grad * g
        else:
            weight -= self.lr * self.rescale_grad * grad

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def create_state_multi_precision(self, index, weight):
        return None

    def set_learning_rate(self, lr):
        self.lr = lr


class TestDistributedOptimizer:
    def test_rescale_grad_folds_average(self):
        opt = hvd.DistributedOptimizer(_FakeSGD(rescale_grad=1.0))
        assert opt.rescale_grad == pytest.approx(1.0 / WORLD)

    def test_update_allreduces_and_applies(self):
        """allreduce(sum) x rescale_grad/size == the distributed average,
        exactly the reference's equivalence (horovod/mxnet/__init__.py:
        44-46)."""
        base = _FakeSGD(lr=1.0, rescale_grad=1.0)
        opt = hvd.DistributedOptimizer(base)
        w = np.full(3, 10.0, np.float32)
        g = np.ones(3, np.float32)
        opt.update(0, w, g, None)
        # replicated world: summed grad = g * WORLD; update subtracts
        # lr * (1/WORLD) * (g*WORLD) = g
        np.testing.assert_allclose(w, np.full(3, 9.0, np.float32))
        assert base.updates == [0]

    def test_update_list_indices_named_by_index(self):
        base = _FakeSGD(lr=1.0, rescale_grad=1.0)
        opt = hvd.DistributedOptimizer(base)
        ws = [np.full(2, 5.0, np.float32), np.full(2, 7.0, np.float32)]
        gs = [np.ones(2, np.float32), 2 * np.ones(2, np.float32)]
        opt.update_multi_precision([3, 4], ws, gs, [None, None])
        np.testing.assert_allclose(ws[0], np.full(2, 4.0, np.float32))
        np.testing.assert_allclose(ws[1], np.full(2, 5.0, np.float32))
        assert base.updates == [[3, 4]]

    def test_double_wrap_rejected(self):
        opt = hvd.DistributedOptimizer(_FakeSGD())
        with pytest.raises(ValueError):
            hvd.DistributedOptimizer(opt)

    def test_delegation(self):
        opt = hvd.DistributedOptimizer(_FakeSGD(lr=0.5))
        assert opt.lr == 0.5
        opt.set_learning_rate(0.25)
        assert opt._optimizer.lr == 0.25
        assert opt.create_state_multi_precision(0, None) is None


class TestTrainerAndBroadcast:
    def test_trainer_is_a_deliberate_limit(self):
        """DistributedTrainer is NOT implemented (r5: the Gluon subclass
        could never be constructed without real MXNet — PARITY.md
        'Deliberate limits'); the name fails loud with a pointer."""
        with pytest.raises(ImportError, match="Deliberate limits"):
            hvd.DistributedTrainer({}, _FakeSGD())

    def test_broadcast_parameters_dict(self):
        params = {"b": np.arange(3, dtype=np.float32),
                  "a": np.ones((2, 2), np.float32),
                  "skip": None}
        hvd.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(params["b"],
                                   np.arange(3, dtype=np.float32))

    def test_broadcast_parameters_bad_type(self):
        with pytest.raises(ValueError):
            hvd.broadcast_parameters([np.ones(2)])
