"""Differential tests: native cycle engine (cpp/cycle.cc) vs the Python
reference implementations.

The reference keeps the per-cycle hot path native (reference:
horovod/common/response_cache.cc, controller.cc:551-672 FuseResponses);
here the Python implementations define the semantics and the C++ engine
must match them operation-for-operation — randomized sequences assert
equal observable state (return values, bit numbering, LRU eviction order,
fused groupings) at every step.
"""

import random

import pytest

from horovod_tpu.runtime import fusion, message as msg, types
from horovod_tpu.runtime.native import native_built
from horovod_tpu.runtime.response_cache import (CacheState,
                                                NativeResponseCache,
                                                ResponseCache)

pytestmark = pytest.mark.skipif(not native_built(),
                                reason="native library unavailable")


def _req(name, rtype=types.ALLREDUCE, dtype="float32", shape=(4,), root=0,
         average=True, rank=0, reduce_op=None):
    rop = reduce_op or ("average" if average else "sum")
    return msg.Request(rank, rtype, name, dtype, shape, root, rop)


def _resp(req):
    return msg.Response(req.request_type, [req.tensor_name])


class TestCacheDifferential:
    def _pair(self, capacity):
        return ResponseCache(capacity), NativeResponseCache(capacity)

    def test_basic_roundtrip(self):
        py, nat = self._pair(4)
        r = _req("a")
        for c in (py, nat):
            assert c.cached(r) == CacheState.MISS
            bit = c.put(_resp(r), r)
            assert bit == 0
            assert c.cached(r) == CacheState.HIT
            assert c.bit_for_name("a") == 0
            got = c.get_by_bit(0)
            assert got is not None and got.tensor_names == ["a"]
            assert c.get_by_bit(7) is None
            assert len(c) == 1

    def test_params_change_is_invalid(self):
        py, nat = self._pair(4)
        r = _req("a", shape=(4,))
        r2 = _req("a", shape=(8,))
        for c in (py, nat):
            c.put(_resp(r), r)
            assert c.cached(r2) == CacheState.INVALID

    def test_capacity_zero_disabled(self):
        py, nat = self._pair(0)
        r = _req("a")
        for c in (py, nat):
            assert c.put(_resp(r), r) == -1
            assert c.cached(r) == CacheState.MISS
            assert len(c) == 0

    def test_randomized_sequences_agree(self):
        rng = random.Random(0)
        names = [f"t{i}" for i in range(12)]
        dtypes = ["float32", "bfloat16"]
        for trial in range(30):
            py, nat = self._pair(capacity=rng.choice([1, 2, 3, 5, 8]))
            for step in range(rng.randint(10, 60)):
                op = rng.choice(["put", "cached", "get", "invalidate",
                                 "bit", "len"])
                name = rng.choice(names)
                r = _req(name, dtype=rng.choice(dtypes),
                         shape=(rng.choice([2, 4]),))
                ctx = f"trial {trial} step {step} op {op} name {name}"
                if op == "put":
                    assert py.put(_resp(r), r) == nat.put(_resp(r), r), ctx
                elif op == "cached":
                    assert py.cached(r) == nat.cached(r), ctx
                elif op == "get":
                    bit = rng.randint(0, 8)
                    a, b = py.get_by_bit(bit), nat.get_by_bit(bit)
                    assert (a is None) == (b is None), ctx
                    if a is not None:
                        assert a.tensor_names == b.tensor_names, ctx
                elif op == "invalidate":
                    py.invalidate(name)
                    nat.invalidate(name)
                elif op == "bit":
                    assert py.bit_for_name(name) == nat.bit_for_name(name), \
                        ctx
                else:
                    assert len(py) == len(nat), ctx

    def test_eviction_and_bit_reuse_order(self):
        """Fill past capacity; the evicted (LRU) entry's bit must be
        recycled lowest-first, identically on both sides."""
        py, nat = self._pair(2)
        for c in (py, nat):
            assert c.put(_resp(_req("a")), _req("a")) == 0
            assert c.put(_resp(_req("b")), _req("b")) == 1
            # touch "a" so "b" is LRU
            assert c.get_by_bit(0).tensor_names == ["a"]
            assert c.put(_resp(_req("c")), _req("c")) == 1  # evicts b, bit 1
            assert c.bit_for_name("b") is None
            c.invalidate("a")
            assert c.put(_resp(_req("d")), _req("d")) == 0  # reuses bit 0


class TestFusionDifferential:
    def _random_case(self, rng):
        n = rng.randint(0, 14)
        responses, reqs = [], {}
        for i in range(n):
            name = f"t{i}"
            kind = rng.choice([types.ALLREDUCE, types.ALLREDUCE,
                               types.ALLGATHER, types.BROADCAST,
                               types.ERROR])
            dtype = rng.choice(["float32", "bfloat16", "int32"])
            shape = (rng.choice([1, 8, 64, 1024]),)
            reqs[name] = _req(name, rtype=kind if kind != types.ERROR
                              else types.ALLREDUCE, dtype=dtype, shape=shape,
                              average=rng.choice([True, False]))
            if kind == types.ERROR:
                responses.append(msg.Response(types.ERROR, [name], "boom"))
            elif kind == types.ALLGATHER:
                responses.append(msg.Response(types.ALLGATHER, [name],
                                              tensor_sizes=[1, 2]))
            else:
                responses.append(msg.Response(kind, [name]))
        threshold = rng.choice([0, 64, 4096, 1 << 20])
        return responses, reqs, threshold

    def _assert_equal(self, a, b):
        assert len(a) == len(b)
        for ra, rb in zip(a, b):
            assert ra.response_type == rb.response_type
            assert ra.tensor_names == rb.tensor_names
            assert ra.error_message == rb.error_message
            assert ra.tensor_sizes == rb.tensor_sizes

    def test_randomized_agree(self):
        rng = random.Random(1)
        for _ in range(200):
            responses, reqs, threshold = self._random_case(rng)
            py = fusion.fuse_responses_py(list(responses), reqs, threshold)
            nat = fusion.fuse_responses_native(list(responses), reqs,
                                               threshold)
            assert nat is not None
            self._assert_equal(py, nat)

    def test_lookahead_preserved(self):
        """A stray non-joinable response between joinable ones must not
        break the bin (the reference's look-ahead, controller.cc:595-650)."""
        reqs = {
            "a": _req("a", dtype="bfloat16", shape=(8,)),
            "x": _req("x", dtype="float32", shape=(8,)),
            "b": _req("b", dtype="bfloat16", shape=(8,)),
        }
        responses = [msg.Response(types.ALLREDUCE, [n]) for n in "axb"]
        out = fusion.fuse_responses_native(responses, reqs, 1 << 20)
        assert [r.tensor_names for r in out] == [["a", "b"], ["x"]]


class TestControllerUsesNative:
    def test_factory_prefers_native(self, monkeypatch):
        from horovod_tpu.runtime.response_cache import make_response_cache

        assert isinstance(make_response_cache(4), NativeResponseCache)
        monkeypatch.setenv("HOROVOD_NATIVE_CYCLE", "0")
        assert isinstance(make_response_cache(4), ResponseCache)
