"""DistributedOptimizer / gradient API / callback tests.

Mirrors the reference's optimizer and gradient tests (reference:
test/test_tensorflow.py:684-977 gradient correctness, test_keras.py
callback coverage) plus an e2e convergence check like the reference's MNIST
examples (reference: examples/pytorch_mnist.py usage pattern).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def _make_data(key, n=64):
    w_true = jnp.array([[2.0], [-3.0]])
    x = jax.random.normal(key, (n, 2))
    return x, x @ w_true


class TestFusedAdamW:
    def test_matches_optax_adamw(self, hvd_flat):
        """The Pallas single-pass adamw must track optax.adamw step for
        step (same hyperparameters, same state layout) within f32
        round-off over several updates, on a tree with both Pallas-sized
        and small (jnp fallback) leaves."""
        from horovod_tpu.ops.pallas import fused_adamw

        rng = np.random.RandomState(0)
        params = {
            "big": jnp.asarray(rng.randn(16384 * 2), jnp.float32),
            "mat": jnp.asarray(rng.randn(256, 128), jnp.float32),
            "small": jnp.asarray(rng.randn(7), jnp.float32),
        }
        lr, wd = 1e-2, 1e-3
        ref_tx = optax.adamw(lr, weight_decay=wd)
        ref_state = ref_tx.init(params)
        fused = fused_adamw(lr, weight_decay=wd)
        state = fused.init(params)

        ref_p = params
        p = params
        for i in range(4):
            grads = jax.tree_util.tree_map(
                lambda a, s=i: jnp.asarray(
                    np.random.RandomState(10 + s).randn(*a.shape),
                    jnp.float32), params)
            upd, ref_state = ref_tx.update(grads, ref_state, ref_p)
            ref_p = optax.apply_updates(ref_p, upd)
            p, state = fused.apply(p, state, grads)
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(p[k]), np.asarray(ref_p[k]),
                    rtol=2e-5, atol=2e-6, err_msg=f"step {i} leaf {k}")
        # state interop: same ScaleByAdamState layout
        np.testing.assert_allclose(np.asarray(state.mu["mat"]),
                                   np.asarray(ref_state[0].mu["mat"]),
                                   rtol=2e-5, atol=2e-6)
        assert int(state.count) == 4

    def test_prime_row_leaf_takes_jnp_path(self):
        """A leaf whose 128-lane row count is prime has no usable block
        divisor — the r4 advisor flagged that searching down to
        block_rows=1 builds a grid of per-row kernel steps (correct but a
        cliff); such leaves must route to the XLA elementwise path and
        still match optax."""
        from horovod_tpu.ops.pallas import fused_adamw

        rng = np.random.RandomState(1)
        # 131 rows of 128 lanes: >= _MIN_PALLAS (16384), n % 128 == 0,
        # prime row count
        params = {"prime": jnp.asarray(rng.randn(131 * 128), jnp.float32)}
        grads = {"prime": jnp.asarray(rng.randn(131 * 128), jnp.float32)}
        lr, wd = 1e-2, 1e-3
        ref_tx = optax.adamw(lr, weight_decay=wd)
        upd, _ = ref_tx.update(grads, ref_tx.init(params), params)
        ref_p = optax.apply_updates(params, upd)
        fused = fused_adamw(lr, weight_decay=wd)
        p, _ = fused.apply(params, fused.init(params), grads)
        np.testing.assert_allclose(np.asarray(p["prime"]),
                                   np.asarray(ref_p["prime"]),
                                   rtol=2e-5, atol=2e-6)


class TestDistributedOptimizer:
    def test_shard_map_training_converges(self, hvd):
        """e2e: per-device microbatches under shard_map, gradients averaged
        by the wrapper across all 8 workers."""
        x, y = _make_data(jax.random.PRNGKey(0))
        params = {"w": jnp.zeros((2, 1))}
        params = hvd.broadcast_parameters(params)
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        opt_state = opt.init(params)
        mesh = hvd.mesh()

        def inner(p, s, xb, yb):
            loss, g = jax.value_and_grad(
                lambda p: jnp.mean((xb @ p["w"] - yb) ** 2))(p)
            updates, s2 = opt.update(g, s, p)
            return loss, optax.apply_updates(p, updates), s2

        step = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
            out_specs=(P(), P(), P()), check_vma=False))

        for _ in range(40):
            loss, params, opt_state = step(params, opt_state, x, y)
        assert float(loss) < 1e-3
        np.testing.assert_allclose(
            np.asarray(params["w"]).ravel(), [2.0, -3.0], atol=0.05)

    def test_plain_jit_noop_reduction(self, hvd):
        """Under plain jit (global batch), the wrapper must be a no-op:
        gradients of a global-mean loss are already the global average."""
        x, y = _make_data(jax.random.PRNGKey(1))
        params = {"w": jnp.zeros((2, 1))}
        opt_plain = optax.sgd(0.1)
        opt_dist = hvd.DistributedOptimizer(optax.sgd(0.1))
        sp, sd = opt_plain.init(params), opt_dist.init(params)

        def g(p):
            return jax.grad(lambda p: jnp.mean((x @ p["w"] - y) ** 2))(p)

        @jax.jit
        def both(p, sp, sd):
            grads = g(p)
            up, _ = opt_plain.update(grads, sp, p)
            ud, _ = opt_dist.update(grads, sd, p)
            return up, ud

        up, ud = both(params, sp, sd)
        np.testing.assert_allclose(np.asarray(up["w"]), np.asarray(ud["w"]))

    def test_gradient_accumulation(self, hvd):
        """backward_passes_per_step accumulates N micro-batches between
        updates (reference: torch/__init__.py:82-143)."""
        params = {"w": jnp.ones((2,))}
        opt = hvd.DistributedOptimizer(
            optax.sgd(1.0), backward_passes_per_step=2)
        s = opt.init(params)
        g = {"w": jnp.ones((2,))}
        u1, s = opt.update(g, s, params)
        # first micro-batch: no update applied yet
        np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)
        u2, s = opt.update(g, s, params)
        # second: applies update from the mean of accumulated grads
        np.testing.assert_allclose(np.asarray(u2["w"]), -1.0)

    def test_compression_roundtrip_dtype(self, hvd):
        params = {"w": jnp.ones((4,), jnp.float32)}
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=hvd.Compression.fp16)
        s = opt.init(params)
        g = {"w": jnp.full((4,), 0.25, jnp.float32)}
        u, _ = opt.update(g, s, params)
        assert u["w"].dtype == jnp.float32

    def test_bad_backward_passes(self, hvd):
        with pytest.raises(ValueError, match=">= 1"):
            hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=0)


class TestDistributedGradientTape:
    def test_grad_fn_wrapping(self, hvd):
        """reference: tensorflow/__init__.py:323-376."""
        def loss(p):
            return jnp.sum(p ** 2)

        wrapped = hvd.DistributedGradientTape(jax.grad(loss))
        g = wrapped(jnp.array([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [2.0, 4.0])

    def test_value_and_grad_wrapping(self, hvd):
        wrapped = hvd.DistributedGradientTape(
            jax.value_and_grad(lambda p: jnp.sum(p ** 2)),
            returns="value_and_grads")
        v, g = wrapped(jnp.array([3.0]))
        np.testing.assert_allclose(float(v), 9.0)
        np.testing.assert_allclose(np.asarray(g), [6.0])

    def test_grads_and_aux_wrapping(self, hvd):
        wrapped = hvd.DistributedGradientTape(
            jax.grad(lambda p: (jnp.sum(p ** 2), {"n": 1}), has_aux=True),
            returns="grads_and_aux")
        g, aux = wrapped(jnp.array([2.0]))
        np.testing.assert_allclose(np.asarray(g), [4.0])
        assert aux == {"n": 1}

    def test_tuple_params_grads_not_misparsed(self, hvd):
        # plain jax.grad over 2-tuple params returns a 2-tuple of grads;
        # default returns="grads" must reduce both, not treat it as
        # (value, grads)
        wrapped = hvd.DistributedGradientTape(
            jax.grad(lambda ab: jnp.sum(ab[0] ** 2) + jnp.sum(ab[1] ** 3)))
        ga, gb = wrapped((jnp.array([1.0]), jnp.array([2.0])))
        np.testing.assert_allclose(np.asarray(ga), [2.0])
        np.testing.assert_allclose(np.asarray(gb), [12.0])

    def test_bad_returns_mode(self, hvd):
        with pytest.raises(ValueError, match="returns must be"):
            hvd.DistributedGradientTape(lambda: None, returns="bogus")


class TestBroadcastState:
    def test_broadcast_parameters_replicates(self, hvd):
        params = {"a": jnp.ones((2, 2)), "b": {"c": jnp.zeros(3)}}
        out = hvd.broadcast_parameters(params)
        assert out["a"].sharding.is_fully_replicated
        np.testing.assert_allclose(np.asarray(out["b"]["c"]), 0.0)

    def test_broadcast_optimizer_state(self, hvd):
        opt = optax.adam(1e-3)
        s = opt.init({"w": jnp.ones((2,))})
        out = hvd.broadcast_optimizer_state(s)
        # non-array leaves (counters) survive; array leaves broadcast
        leaves = jax.tree_util.tree_leaves(out)
        assert len(leaves) == len(jax.tree_util.tree_leaves(s))

    def test_broadcast_object_single_process(self, hvd):
        assert hvd.broadcast_object({"epoch": 3}) == {"epoch": 3}


class TestCallbacks:
    def test_metric_average(self, hvd):
        from horovod_tpu import callbacks

        m = callbacks.average_metrics({"loss": jnp.float32(2.0)})
        np.testing.assert_allclose(float(m["loss"]), 2.0)

    def test_warmup_schedule(self, hvd):
        from horovod_tpu import callbacks

        sched = callbacks.warmup_scaled_schedule(
            base_lr=0.1, warmup_epochs=2, steps_per_epoch=10, size=8)
        np.testing.assert_allclose(float(sched(0)), 0.1)
        np.testing.assert_allclose(float(sched(20)), 0.8, rtol=1e-5)
        np.testing.assert_allclose(float(sched(10)), 0.45, rtol=1e-5)
        np.testing.assert_allclose(float(sched(100)), 0.8, rtol=1e-5)

    def test_warmup_with_after_schedule(self, hvd):
        from horovod_tpu import callbacks

        sched = callbacks.warmup_scaled_schedule(
            base_lr=0.1, warmup_epochs=1, steps_per_epoch=10, size=8,
            after=lambda e: 0.1 ** (e // 30))
        np.testing.assert_allclose(float(sched(10)), 0.8, rtol=1e-5)
        np.testing.assert_allclose(float(sched(10 + 300)), 0.08, rtol=1e-5)

    def test_broadcast_callback(self, hvd):
        from horovod_tpu import callbacks

        cb = callbacks.BroadcastGlobalVariablesCallback(root_rank=0)
        state = {"w": jnp.ones((2,))}
        out = cb.on_train_begin(state)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)

    def test_lr_schedule_callback(self, hvd):
        from horovod_tpu import callbacks

        cb = callbacks.LearningRateScheduleCallback(
            base_lr=1.0, multiplier=lambda e: 0.1 ** (e // 2))
        cb.on_epoch_begin(0, None)
        assert cb.lr == pytest.approx(1.0)
        cb.on_epoch_begin(2, None)
        assert cb.lr == pytest.approx(0.1)
