"""Unit + e2e coverage for the paged KV-cache subsystem (serve/paging.py;
docs/inference.md "Paged KV cache").

Pinned-down contracts:

* the :class:`PagePool` block allocator — refcounted free list, scratch
  page 0 never allocated, reclaim hook re-entrancy, exhaustion;
* the :class:`PrefixCache` — rolling-hash block walk, exact replay
  entries, LRU eviction dropping page refs, pressure reclaim;
* page-aware admission in the :class:`ContinuousBatcher` — pool pages
  as the committed capacity, the prefix-probe discount, preempt-newest
  back to the queue FRONT;
* the :class:`PagedDecodeEngine` — token-for-token parity with the
  uncached ``apply`` through page-table gathers, copy-on-write isolation
  between prefix sharers, exact-replay with ZERO prefill compute, zero
  steady-state compiles under slot churn + page growth + COW + hits,
  exhaustion rollback;
* e2e through ``hvd.serve()``: preemption under pool pressure resumes
  from the queue front and still delivers the FULL token budget, and the
  chaos cell — a replica killed mid-decode reclaims every request-held
  page (``request_held == 0``) while the survivor completes the work.
"""

import math

import pytest

from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.paging import (PagePool, PagePoolExhausted,
                                      PrefixCache, auto_pool_pages)
from horovod_tpu.serve.queue import Request


def _req(uid, prompt, max_new=8):
    return Request(uid=uid, prompt=list(prompt), max_new_tokens=max_new,
                   submitted_s=0.0)


# --------------------------------------------------------------- PagePool

class TestPagePool:
    def test_alloc_ref_unref_cycle(self):
        pool = PagePool(pages=5, page_tokens=16)
        assert pool.allocatable == 4
        got = [pool.alloc() for _ in range(4)]
        assert sorted(got) == [1, 2, 3, 4]      # page 0 is scratch
        assert pool.free_count() == 0 and pool.used_count() == 4
        pool.ref(got[0])
        assert pool.refcount(got[0]) == 2
        assert pool.unref(got[0]) is False      # still shared
        assert pool.unref(got[0]) is True       # last ref frees
        assert pool.free_count() == 1
        assert pool.alloc() == got[0]           # recycled

    def test_exhaustion_and_bad_refs(self):
        pool = PagePool(pages=3, page_tokens=16)
        pool.alloc(), pool.alloc()
        with pytest.raises(PagePoolExhausted):
            pool.alloc()
        with pytest.raises(ValueError):
            pool.ref(0)                         # scratch is unallocatable
        with pytest.raises(ValueError):
            pool.unref(1_000)

    def test_reclaim_hook_runs_outside_lock(self):
        """The hook re-enters pool.unref — it would deadlock if alloc
        held the pool lock across the callback."""
        pool = PagePool(pages=3, page_tokens=16)
        held = [pool.alloc(), pool.alloc()]
        pool.set_reclaim_hook(lambda: pool.unref(held.pop()))
        assert pool.alloc() in (1, 2)           # reclaimed and reissued
        assert pool.stats()["reclaims"] == 1

    def test_too_small_pool_rejected(self):
        with pytest.raises(ValueError):
            PagePool(pages=1, page_tokens=16)

    def test_auto_pool_pages_halves_dense_capacity(self):
        # bench --tiny shape: 4 slots x 96 tokens dense -> 192 paged
        # token rows (12 pages of 16) = exactly 2x lower KV bytes
        assert auto_pool_pages(4, 96, 16) == 12
        # floor: one max_seq request + scratch always fits
        assert auto_pool_pages(1, 48, 16) == 4


# ------------------------------------------------------------ PrefixCache

class TestPrefixCache:
    def _cache(self, pages=8, capacity=16):
        pool = PagePool(pages=pages, page_tokens=4)
        return pool, PrefixCache(pool, capacity)

    def test_block_walk_and_probe(self):
        pool, cache = self._cache()
        prompt = list(range(10))                # 2 full blocks + tail 2
        pages = [pool.alloc() for _ in range(3)]
        cache.insert(prompt, pages, first_token=7, max_abs=1.0)
        assert cache.probe(prompt) == 2
        assert cache.probe(prompt[:8] + [99, 98]) == 2   # same blocks
        assert cache.probe([99] + prompt[1:]) == 0       # first differs
        hit, exact = cache.lookup(prompt[:8] + [99, 98])
        assert hit == pages[:2] and exact is None
        hit, exact = cache.lookup(prompt)
        assert exact is not None
        assert list(exact[0]) == pages and exact[1] == 7

    def test_insert_refs_and_eviction_unrefs(self):
        pool, cache = self._cache()
        prompt = list(range(8))                 # 2 full blocks
        pages = [pool.alloc(), pool.alloc()]
        cache.insert(prompt, pages, 1, 1.0)     # 2 block + 1 exact entry
        assert len(cache) == 3
        # blocks ref once each; the exact entry refs both again
        assert pool.refcount(pages[0]) == 3
        assert pool.refcount(pages[1]) == 3
        cache.release_all()
        assert len(cache) == 0
        assert pool.refcount(pages[0]) == 1     # caller's refs survive
        assert pool.refcount(pages[1]) == 1

    def test_capacity_trim_evicts_lru(self):
        pool, cache = self._cache(capacity=2)
        pages = [pool.alloc(), pool.alloc()]
        cache.insert(list(range(8)), pages, 1, 1.0)
        assert len(cache) == 2                  # block 0 (LRU) trimmed
        assert cache.evictions == 1
        assert cache.probe(list(range(8))) == 0  # depth-0 gone: no chain

    def test_reclaim_one_frees_under_pressure(self):
        pool, cache = self._cache(pages=4)      # 3 allocatable
        pages = [pool.alloc(), pool.alloc()]
        cache.insert(list(range(8)), pages, 1, 1.0)
        pool.unref(pages[0]), pool.unref(pages[1])   # cache is sole owner
        pool.set_reclaim_hook(cache.reclaim_one)
        for _ in range(3):                      # 1 free + 2 reclaimable
            pool.alloc()
        assert len(cache) == 0                  # pressure drained the LRU
        with pytest.raises(PagePoolExhausted):
            pool.alloc()                        # cache empty, truly full

    def test_hash_collision_verified_against_tokens(self):
        pool, cache = self._cache()
        page = pool.alloc()
        cache.insert([1, 2, 3, 4], [page], 1, 1.0)
        # same (depth, hash) key would need hash([1,2,3,4]) == hash of a
        # different block; lookup verifies stored tokens so a mismatch
        # is a miss, never a wrong page
        hit, _ = cache.lookup([1, 2, 3, 5])
        assert hit == []


# ----------------------------------------------- page-aware admission

class TestPagedAdmission:
    def _batcher(self, pool_pages=4, page_tokens=16, probe=None,
                 slots=4, max_seq=48):
        return ContinuousBatcher(
            num_slots=slots, max_batch_tokens=10_000, admission_ms=50.0,
            decode_block=8, max_seq=max_seq, page_tokens=page_tokens,
            pool_pages=pool_pages, prefix_probe=probe)

    def test_pool_pages_cap_admission(self):
        # each request: prompt 17 + max_new 32 -> 48 written -> 3 pages
        b = self._batcher()
        for uid in ("a", "b"):
            b.offer(_req(uid, range(1, 18), max_new=32), now=0.0)
        admitted = b.admit(0.0)
        assert [a.request.uid for a in admitted] == ["a"]
        assert admitted[0].page_cost == 3
        assert b.committed_pages() == 3         # 3 + 3 > 4: b waits
        assert b.waiting() == 1

    def test_prefix_probe_discounts_page_cost(self):
        b = self._batcher(probe=lambda prompt: 1)
        for uid in ("a", "b"):
            b.offer(_req(uid, range(1, 18), max_new=32), now=0.0)
        admitted = b.admit(0.0)
        assert [a.request.uid for a in admitted] == ["a", "b"]
        assert all(a.page_cost == 2 for a in admitted)

    def test_single_request_capped_to_pool(self):
        # pool capacity 4*16 = 64 tokens; prompt 40 + max_new 64 would
        # write past it -> max_tokens capped (finish="cache_limit"),
        # the paged analogue of the dense max_seq cap
        b = self._batcher(max_seq=None)
        b.offer(_req("a", range(40), max_new=64), now=0.0)
        (a,) = b.admit(0.0)
        assert a.max_tokens == 4 * 16 - 40 + 1 == 25
        assert a.capped

    def test_preempt_newest_to_queue_front(self):
        b = self._batcher(pool_pages=100)
        for uid in ("old", "mid", "new"):
            b.offer(_req(uid, range(1, 9)), now=0.0)
        b.admit(0.0)
        assert b.occupancy() == 3
        victim = b.preempt_newest(now=1.0)
        assert victim.request.uid == "new"
        assert b.preemptions == 1
        assert victim.request.requeues == 1
        # requeued to the FRONT: next admission re-admits it first
        b.offer(_req("younger", range(1, 9)), now=1.0)
        readmitted = b.admit(1.0)
        assert [a.request.uid for a in readmitted] == ["new", "younger"]
        # exclude_slot protects the slot mid-prefill
        mid = next(a for a in b.active() if a.request.uid == "mid")
        survivor = b.preempt_newest(exclude_slot=None, now=2.0)
        assert survivor.request.uid == "younger"
        assert b.preempt_newest(exclude_slot=mid.slot, now=2.0) \
               .request.uid != "mid"

    def test_dense_batcher_unaffected(self):
        b = ContinuousBatcher(num_slots=4, max_batch_tokens=10_000,
                              admission_ms=50.0, decode_block=8)
        b.offer(_req("a", range(1, 9)), now=0.0)
        (a,) = b.admit(0.0)
        assert a.page_cost == 0
        assert b.committed_pages() == 0


# -------------------------------------------------------- engine (jax)

@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import Transformer

    model = Transformer(vocab_size=61, d_model=32, num_layers=2,
                        num_heads=2, d_ff=64, max_seq=48, causal=True,
                        dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return model, params


def _uncached_greedy(model, params, prompt, n):
    import jax.numpy as jnp

    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([toks], jnp.int32), train=False)
        out.append(int(jnp.argmax(logits[0, len(toks) - 1])))
        toks.append(out[-1])
    return out


def _engine(model, params, slots=3, **kw):
    """Direct-call engines get a roomy pool (the replica loop is what
    answers PagePoolExhausted; tests that WANT pressure size it down)."""
    from horovod_tpu.serve.paging import PagedDecodeEngine

    kw.setdefault("page_tokens", 16)
    kw.setdefault("pool_pages", 12)
    return PagedDecodeEngine(model, params, num_slots=slots, **kw)


def _generate(eng, slot, prompt, n):
    token, max_abs = eng.prefill(slot, prompt)
    assert math.isfinite(max_abs)
    out, pos = [token], len(prompt)
    for _ in range(n - 1):
        (t,), _ = eng.decode([slot], [out[-1]], [pos])
        out.append(t)
        pos += 1
    return out


def test_paged_parity_across_buckets(tiny_lm):
    """Gathering K/V through traced page tables must be token-for-token
    identical to the uncached apply — across prompt buckets, including
    prompts that span multiple pages."""
    model, params = tiny_lm
    eng = _engine(model, params)
    for slot, prompt in ((0, [5, 4, 3, 2, 1]), (1, list(range(1, 18))),
                        (2, list(range(2, 37)))):
        assert _generate(eng, slot, prompt, 6) == \
            _uncached_greedy(model, params, prompt, 6), len(prompt)


def test_shared_prefix_cow_isolation(tiny_lm):
    """Two requests share a 16-token prefix block; the second reuses the
    first's page and must copy-on-write before its first divergent
    write — both must still match the uncached reference exactly."""
    model, params = tiny_lm
    eng = _engine(model, params)
    shared = list(range(1, 17))
    a, b = shared + [20, 21], shared + [30]
    token_a, _ = eng.prefill(0, a)
    cows0 = eng.cow_copies
    token_b, _ = eng.prefill(1, b)
    assert eng.reused_tokens >= 16              # block hit on b's prefill
    gen = {0: [token_a], 1: [token_b]}
    pos = {0: len(a), 1: len(b)}
    for _ in range(5):
        ids, _ = eng.decode([0, 1], [gen[0][-1], gen[1][-1]],
                            [pos[0], pos[1]])
        for s, t in zip((0, 1), ids):
            gen[s].append(t)
            pos[s] += 1
    assert eng.cow_copies > cows0               # sharing actually copied
    assert gen[0] == _uncached_greedy(model, params, a, 6)
    assert gen[1] == _uncached_greedy(model, params, b, 6)


def test_exact_replay_zero_prefill_compute(tiny_lm):
    """A byte-identical repeat prompt replays the cached pages + first
    token: computed_tokens must NOT move (zero prefill compute), and the
    replayed slot must still decode exactly like the reference."""
    model, params = tiny_lm
    eng = _engine(model, params)
    prompt = list(range(3, 24))
    first = _generate(eng, 0, prompt, 4)
    computed = eng.computed_tokens
    repeat = _generate(eng, 1, prompt, 4)
    assert eng.exact_hits == 1
    assert eng.computed_tokens == computed      # nothing recomputed
    assert repeat == first == _uncached_greedy(model, params, prompt, 4)
    assert eng.prefix_hit_rate() > 0


def test_zero_steady_state_compiles_canary(tiny_lm):
    """Slot churn + page-table growth + COW + prefix hits + preemption
    release must all run through the already-compiled programs: ONE
    decode program, one prefill program per bucket, one COW copy."""
    model, params = tiny_lm
    eng = _engine(model, params, slots=2)
    eng.prefill(0, [1] * 16)                    # bucket 16
    eng.prefill(0, list(range(2, 22)))          # bucket 32
    eng.decode([0], [1], [20])
    warm = eng.compiles_total()
    shared = list(range(2, 18))
    for step in range(6):
        slot = step % 2
        eng.prefill(slot, shared + [25 + step])  # block hit + suffix
        (t,), _ = eng.decode([slot], [3], [17])  # COW + table growth
        eng.decode([slot], [t], [18])
    eng.release_slot(0)                         # preemption release path
    eng.prefill(0, shared + [40])
    eng.decode([0, 1], [1, 2], [17, 19])
    assert eng.compiles_total() == warm
    assert eng.cow_copies > 0
    stats = eng.stats()
    assert stats["pages"]["prefix_hit_rate"] > 0
    assert stats["compiles"]["page_copy"] == 1


def test_exhaustion_rolls_back_and_recovers(tiny_lm):
    """A prefill the pool cannot hold must raise PagePoolExhausted and
    roll back every ref it took — the pool is exactly as before, and the
    same prefill succeeds once a victim releases."""
    model, params = tiny_lm
    eng = _engine(model, params, slots=2, pool_pages=4)  # 3 allocatable
    eng.prefill(0, list(range(1, 34)))          # 33 tokens -> 3 pages
    assert eng.pool.free_count() == 0
    with pytest.raises(PagePoolExhausted):
        eng.prefill(1, list(range(40, 57)))     # needs 2 fresh pages
    assert eng.pool.free_count() == 0           # rollback: nothing leaked
    assert eng._tables[1] == []
    eng.release_slot(0)                         # victim preempted
    token, _ = eng.prefill(1, list(range(40, 57)))
    assert isinstance(token, int)
    assert eng.page_stats()["request_held"] >= 2


def test_release_all_reclaims_every_request_page(tiny_lm):
    """Quarantine path: request_held == 0 after release_all — the pool
    analogue of the fusion-buffer ``leases == 0`` chaos pin."""
    model, params = tiny_lm
    eng = _engine(model, params, slots=3)
    for slot, n in ((0, 5), (1, 20), (2, 33)):
        _generate(eng, slot, list(range(1, n + 1)), 3)
    assert eng.page_stats()["request_held"] > 0
    eng.release_all()
    stats = eng.page_stats()
    assert stats["request_held"] == 0
    # every page is either free or held only by the prefix cache
    assert stats["free"] + len(eng.prefix.held_pages()) \
        == eng.pool.allocatable


def test_paged_pool_bytes_in_memory_ledger(tiny_lm):
    """kv_pages is a first-class device subsystem: the pool registry
    feeds memory.py's ledger and the reconciliation set."""
    from horovod_tpu import memory
    from horovod_tpu.serve import paging

    model, params = tiny_lm
    eng = _engine(model, params)
    assert "kv_pages" in memory.DEVICE_SUBSYSTEMS
    assert paging.total_pool_bytes() >= eng.cache_bytes() > 0
    ledger = memory.tracker().ledger()
    assert ledger["subsystems"]["kv_pages"]["bytes"] >= eng.cache_bytes()


def test_policy_paged_knobs_from_env(monkeypatch):
    from horovod_tpu.serve.api import ServePolicy

    monkeypatch.setenv("HOROVOD_SERVE_PAGED", "1")
    monkeypatch.setenv("HOROVOD_SERVE_PAGE_TOKENS", "32")
    monkeypatch.setenv("HOROVOD_SERVE_PAGE_POOL", "64")
    monkeypatch.setenv("HOROVOD_SERVE_PREFIX_CACHE", "9")
    policy = ServePolicy.from_env()
    assert policy.paged and policy.page_tokens == 32
    assert policy.page_pool == 64 and policy.prefix_cache == 9
    policy = ServePolicy.from_env(paged=False)
    assert not policy.paged


def test_non_power_of_two_page_tokens_rejected(tiny_lm):
    model, params = tiny_lm
    with pytest.raises(ValueError, match="power of two"):
        _engine(model, params, page_tokens=12)


def test_pool_too_small_for_max_seq_rejected(tiny_lm):
    model, params = tiny_lm
    with pytest.raises(ValueError, match="max_seq"):
        _engine(model, params, pool_pages=3)    # 2 allocatable < 3 blocks


# ------------------------------------------------------------ e2e serve

def test_preempted_request_completes_full_budget(tiny_lm):
    """The ISSUE 17 regression pin: under pool pressure the newest
    request is preempted to the queue FRONT and — once pages free — must
    complete with its FULL token budget, counted as a requeue, never
    lost, never truncated."""
    from horovod_tpu.serve import serve as hvd_serve

    model, params = tiny_lm
    handle = hvd_serve(model, params, replicas=1, paged=True,
                       page_tokens=16, page_pool=5, prefix_cache=16,
                       slots=4, max_new_tokens=32, admission_ms=5.0,
                       decode_block=4, max_batch_tokens=4096,
                       quarantine=False)
    try:
        shared = list(range(1, 17))             # one full shared block
        uids = [handle.submit(shared + [17 + i]) for i in range(3)]
        outs = [handle.result(u, timeout=120.0) for u in uids]
        assert all(len(o.tokens) == 32 for o in outs)   # full budget
        assert all(o.finish == "length" for o in outs)
        replica = handle._replicas[0]
        assert replica.engine.preemptions >= 1
        assert sum(o.requeues for o in outs) >= 1
        assert replica.stats()["pages"]["request_held"] == 0
    finally:
        handle.close()


def test_chaos_replica_death_reclaims_pages(tiny_lm):
    """Chaos cell: one replica's decode dies mid-flight. Its requests
    requeue (zero lost), the survivor completes them, and the dead
    replica's pool holds ZERO request pages (request_held == 0)."""
    import time as _time

    from horovod_tpu.serve import serve as hvd_serve

    model, params = tiny_lm
    handle = hvd_serve(model, params, replicas=2, paged=True,
                       page_tokens=16, slots=4, max_new_tokens=4,
                       admission_ms=5.0, decode_block=4,
                       max_batch_tokens=4096, quarantine=True)
    try:
        victim = handle._replicas[0]

        def killed_decode(slots, tokens, positions):
            raise RuntimeError("chaos: replica killed mid-decode")

        victim.engine.decode = killed_decode
        uids, deadline = [], _time.monotonic() + 30.0
        while not victim.quarantined and _time.monotonic() < deadline:
            uids.append(handle.submit(list(range(1, 9)) + [len(uids) % 50]))
            _time.sleep(0.02)
        assert victim.quarantined, "victim replica never pulled work"
        outs = [handle.result(u, timeout=120.0) for u in uids]
        assert all(len(o.tokens) == 4 for o in outs)    # zero lost
        assert all(o.rank == 1 for o in outs if o.requeues)
        assert victim.engine.page_stats()["request_held"] == 0
    finally:
        handle.close()
