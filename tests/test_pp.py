"""Pipeline parallelism: GPipe schedule correctness + training.

Correctness bar: pipelined forward/backward must equal the sequential
stage composition exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import pp

DIM = 8
N_MICRO = 6
MB = 2


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stage_params(rng, n_stages):
    return [
        {"w": jnp.asarray(rng.randn(DIM, DIM).astype(np.float32) * 0.5),
         "b": jnp.asarray(rng.randn(DIM).astype(np.float32) * 0.1)}
        for _ in range(n_stages)
    ]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


class TestPipeline:
    def test_forward_matches_sequential(self, hvd_flat):
        n_stages = hvd_flat.local_size()
        rng = np.random.RandomState(0)
        per_stage = _make_stage_params(rng, n_stages)
        stacked = pp.stack_stage_params(per_stage)
        x = jnp.asarray(rng.randn(N_MICRO, MB, DIM).astype(np.float32))

        def run(stacked, x):
            out = pp.pipeline_apply(_stage_fn, stacked, x, "local")
            return pp.last_stage_value(out, "local")

        piped = jax.jit(jax.shard_map(
            run, mesh=hvd_flat.mesh(),
            in_specs=(P("local"), P()), out_specs=P(),
            check_vma=False))(stacked, x)

        ref = _sequential(per_stage, x.reshape(-1, DIM)).reshape(
            N_MICRO, MB, DIM)
        np.testing.assert_allclose(np.asarray(piped), np.asarray(ref),
                                   atol=1e-6)

    def test_gradients_match_sequential(self, hvd_flat):
        n_stages = hvd_flat.local_size()
        rng = np.random.RandomState(1)
        per_stage = _make_stage_params(rng, n_stages)
        stacked = pp.stack_stage_params(per_stage)
        x = jnp.asarray(rng.randn(N_MICRO, MB, DIM).astype(np.float32))
        target = jnp.asarray(rng.randn(N_MICRO, MB, DIM).astype(np.float32))

        def piped_loss(stacked, x):
            def inner(stacked, x):
                out = pp.pipeline_apply(_stage_fn, stacked, x, "local")
                loss = jnp.mean((out - target) ** 2)
                return pp.last_stage_value(loss, "local")

            return jax.shard_map(
                inner, mesh=hvd_flat.mesh(),
                in_specs=(P("local"), P()), out_specs=P(),
                check_vma=False)(stacked, x)

        g_piped = jax.jit(jax.grad(piped_loss))(stacked, x)

        def seq_loss(per_stage_flat):
            out = _sequential(per_stage_flat, x.reshape(-1, DIM)).reshape(
                N_MICRO, MB, DIM)
            return jnp.mean((out - target) ** 2)

        g_seq = jax.grad(seq_loss)(per_stage)
        g_seq_stacked = pp.stack_stage_params(g_seq)
        for a, b in zip(jax.tree_util.tree_leaves(g_piped),
                        jax.tree_util.tree_leaves(g_seq_stacked)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_pipeline_training_converges(self, hvd_flat):
        """End-to-end: SGD over pipelined stages memorizes a mapping."""
        n_stages = hvd_flat.local_size()
        rng = np.random.RandomState(2)
        stacked = pp.stack_stage_params(_make_stage_params(rng, n_stages))
        x = jnp.asarray(rng.randn(N_MICRO, MB, DIM).astype(np.float32))
        target = jnp.asarray(np.tanh(rng.randn(N_MICRO, MB, DIM))
                             .astype(np.float32))
        opt = optax.adam(3e-3)
        state = opt.init(stacked)

        def loss_fn(stacked, x):
            def inner(stacked, x):
                out = pp.pipeline_apply(_stage_fn, stacked, x, "local")
                loss = jnp.mean((out - target) ** 2)
                return pp.last_stage_value(loss, "local")

            return jax.shard_map(
                inner, mesh=hvd_flat.mesh(),
                in_specs=(P("local"), P()), out_specs=P(),
                check_vma=False)(stacked, x)

        @jax.jit
        def step(stacked, state, x):
            loss, g = jax.value_and_grad(loss_fn)(stacked, x)
            updates, state = opt.update(g, state, stacked)
            return loss, optax.apply_updates(stacked, updates), state

        losses = []
        for _ in range(150):
            loss, stacked, state = step(stacked, state, x)
            losses.append(float(loss))
        assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])
