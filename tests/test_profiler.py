"""Step profiler tests (ISSUE.md PR 6): phase attribution, comm-overlap
accounting, rolling MFU, the merged cross-rank trace, and the
``GET /profile`` endpoint.

The load-bearing guarantees: (1) the four phases sum to the step wall
time exactly — the report can never attribute more (or less) time than
passed; (2) a synchronous allreduce reports ~zero hidden comm while a
depth-2 pipelined pair reports a positive hidden fraction — the
measurement the overlap campaign (ROADMAP item 5) will optimize; (3) the
merged trace is valid Chrome JSON with per-lane monotonic timestamps and
per-rank clock correction applied.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.runtime import message as msg, types

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "profiler_worker.py")


@pytest.fixture(autouse=True)
def _pristine_profiler_state(monkeypatch):
    """These tests assert against the module-global profiler's enabled
    state; start each from a known-disabled baseline so an earlier test
    that enabled profiling (e.g. via bench.enable_profiler) can't leak
    into the assertions here. Runs before ``prof``, which re-enables."""
    from horovod_tpu import profiler

    monkeypatch.delenv("HOROVOD_PROFILE", raising=False)
    monkeypatch.delenv("HOROVOD_PROFILE_DIR", raising=False)
    profiler.configure()
    # drain the bounded history rings too: the relative-slicing idiom
    # (n0 = len(history()); history()[n0:]) silently returns [] once the
    # deque hits maxlen (64) — which it always has by this point of a
    # full-suite run
    p = profiler._profiler
    p._steps.clear()
    p._trace_events.clear()
    p._mfu_window.clear()
    p._auto_rec = None
    yield


@pytest.fixture
def prof(monkeypatch):
    """Profiler enabled for the test, disabled (and ring-isolated via
    relative slicing) afterwards."""
    from horovod_tpu import profiler

    monkeypatch.setenv("HOROVOD_PROFILE", "1")
    profiler.configure()
    yield profiler
    monkeypatch.delenv("HOROVOD_PROFILE", raising=False)
    profiler.configure()


class TestPhaseAttribution:
    def test_phases_sum_to_wall_exactly(self, hvd, prof):
        with prof.step("attributed") as rec:
            with prof.annotate("host"):
                time.sleep(0.02)
            time.sleep(0.03)  # unannotated -> compute
            with prof.annotate("optimizer"):
                time.sleep(0.01)
        b = rec.breakdown
        assert b is not None
        assert abs(sum(b["phases"].values()) - b["wall_seconds"]) < 1e-9
        assert b["phases"]["host"] == pytest.approx(0.02, abs=0.015)
        assert b["phases"]["optimizer"] == pytest.approx(0.01, abs=0.015)
        assert b["phases"]["compute"] > 0.02

    def test_input_aliases_host_and_unknown_phase_raises(self, hvd, prof):
        with prof.step() as rec:
            with prof.annotate("input"):
                time.sleep(0.005)
        assert rec.breakdown["phases"]["host"] > 0
        with pytest.raises(ValueError):
            prof.annotate("backward").__enter__()

    def test_auto_step_via_distributed_optimizer(self, hvd, prof):
        """The eager DistributedOptimizer path needs no explicit
        bracketing: every update is an auto step with a positive
        optimizer phase."""
        import optax

        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": np.ones(8, np.float32)}
        state = opt.init(params)
        n0 = len(prof.history())
        for _ in range(3):
            grads = {"w": np.full(8, 0.5, np.float32)}
            _, state = opt.update(grads, state, params)
        prof.auto_step()  # close the last implicit step
        steps = prof.history()[n0:]
        assert len(steps) >= 3
        assert all(s["auto"] for s in steps)
        assert any(s["phases"]["optimizer"] > 0 for s in steps)

    def test_disabled_profiler_records_nothing(self, hvd):
        from horovod_tpu import profiler

        assert not profiler.enabled()
        n0 = len(profiler.history())
        with profiler.step("off") as rec:
            pass
        profiler.auto_step()
        assert rec is None
        assert len(profiler.history()) == n0


class TestCommOverlap:
    def _entries(self, hvd, tag, j=0):
        return [types.TensorTableEntry(
            name=f"prof/{tag}/t{j}",
            tensor=hvd.stack_per_worker(
                [np.full((256,), float(i + j), "float32")
                 for i in range(hvd.size())]),
            reduce_op=types.REDUCE_SUM)]

    def test_sync_allreduce_fully_exposed(self, hvd, prof):
        from horovod_tpu.runtime.runtime import get_runtime

        ex = get_runtime().executor
        with prof.step("sync") as rec:
            entries = self._entries(hvd, "sync")
            pend = ex.dispatch(
                msg.Response(types.ALLREDUCE, [e.name for e in entries]),
                entries)
            pend.complete()  # depth 1: drain immediately after dispatch
        comm = rec.breakdown["comm"]
        assert comm["total_seconds"] > 0
        assert comm["hidden_fraction"] < 0.05

    def test_pipelined_dispatch_hides_comm(self, hvd, prof):
        from horovod_tpu.runtime.runtime import get_runtime

        ex = get_runtime().executor
        with prof.step("depth2") as rec:
            pends = []
            for j in range(2):  # depth 2: both in flight before any drain
                entries = self._entries(hvd, "depth2", j)
                pends.append(ex.dispatch(
                    msg.Response(types.ALLREDUCE,
                                 [e.name for e in entries]), entries))
            time.sleep(0.01)  # overlapped caller work while parked
            for pend in pends:
                pend.complete()
        comm = rec.breakdown["comm"]
        assert comm["total_seconds"] > 0
        assert comm["hidden_fraction"] > 0.0
        assert comm["hidden_fraction_bytes"] > 0.0

    def test_step_metrics_move(self, hvd, prof):
        from horovod_tpu.profiler import _HIDDEN_FRACTION, _STEP_SECONDS

        count0 = _STEP_SECONDS.labels().count
        with prof.step("metrics"):
            time.sleep(0.001)
        assert _STEP_SECONDS.labels().count == count0 + 1
        assert 0.0 <= _HIDDEN_FRACTION.value <= 1.0


class TestMfu:
    def test_gauge_matches_rolling_formula(self, hvd, prof):
        from horovod_tpu.profiler import _MFU

        flops, peak = 2.0e9, 1.0e12
        prof.set_flops_per_step(flops, peak_flops_per_chip=peak)
        n0 = len(prof.history())
        for _ in range(3):
            with prof.step():
                time.sleep(0.005)
        steps = prof.history()[n0:]
        per_step = [flops / s["wall_seconds"] / peak for s in steps]
        for s, expect in zip(steps, per_step):
            assert s["mfu"] == pytest.approx(expect, rel=1e-12)
        window = [s["mfu"] for s in prof.history()
                  if s.get("mfu") is not None]
        assert _MFU.value == pytest.approx(sum(window) / len(window),
                                           rel=1e-12)
        prof.set_flops_per_step(None)

    def test_no_peak_no_mfu(self, hvd, prof):
        prof.profiler()._peak_flops = None
        prof.set_flops_per_step(1e9)  # no peak hint -> mfu stays unset
        with prof.step() as rec:
            pass
        assert rec.breakdown["mfu"] is None


class TestSummaryAndState:
    def test_summary_aggregates(self, hvd, prof):
        n0 = len(prof.history())
        for _ in range(2):
            with prof.step():
                time.sleep(0.002)
        s = prof.summary()
        assert s["steps"] >= 2 and s["steps"] >= len(prof.history()[n0:])
        assert set(s["step_breakdown"]) == set(
            ("host", "compute", "exposed_comm", "optimizer"))
        assert 0.0 <= s["comm_hidden_fraction"] <= 1.0

    def test_flight_recorder_state_provider(self, hvd, prof):
        from horovod_tpu import flight_recorder

        with prof.step("flight"):
            pass
        state = flight_recorder.recorder().snapshot("test")["state"]
        assert "profiler" in state
        assert state["profiler"]["steps"]

    def test_profile_endpoint(self, hvd, prof):
        from horovod_tpu.metrics import registry

        with prof.step("serve"):
            pass
        reg = registry()
        port = reg.serve(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/profile", timeout=5) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "application/json")
                doc = json.loads(resp.read())
        finally:
            reg.stop_server()
        assert doc["schema"] == "horovod-profiler-v1"
        assert doc["enabled"] is True
        assert doc["steps"], "profiled steps missing from GET /profile"

    def test_dump_writes_schema_and_markers(self, hvd, prof, tmp_path):
        with prof.step("dumped"):
            time.sleep(0.001)
        snap = prof.dump(str(tmp_path / "profile-rank-0.json"), ship=False)
        doc = json.load(open(tmp_path / "profile-rank-0.json"))
        assert doc["schema"] == "horovod-profiler-v1"
        assert doc["steps"] == snap["steps"]
        assert any(e["ph"] == "X" and e["name"] == "dumped"
                   for e in doc["trace_events"])


def _fake_dump(rank, t0, offset=0.0, n_steps=3):
    events = []
    steps = []
    for i in range(n_steps):
        start = t0 + 0.1 * i
        steps.append({"step": i + 1, "name": f"step {i}", "auto": False,
                      "t_start": start, "wall_seconds": 0.05,
                      "phases": {"host": 0.01, "compute": 0.03,
                                 "exposed_comm": 0.005, "optimizer": 0.005},
                      "comm": {"total_seconds": 0.01,
                               "exposed_seconds": 0.005, "bytes": 1024,
                               "hidden_fraction": 0.5,
                               "hidden_fraction_bytes": 0.5},
                      "mfu": 0.4})
        events.append({"ph": "X", "pid": 0, "tid": 0, "ts": start * 1e6,
                       "dur": 0.05 * 1e6, "name": f"step {i}"})
    return {"schema": "horovod-profiler-v1", "rank": rank,
            "launch_rank": rank, "clock_offset_seconds": offset,
            "steps": steps, "trace_events": events,
            "flight_events": [{"t": t0, "kind": "init", "rank": rank}]}


class TestMergedTrace:
    def test_merge_is_valid_chrome_trace(self, tmp_path):
        from horovod_tpu import profiler

        t0 = 1700000000.0
        for rank, offset in ((0, 0.0), (1, 2.5)):
            with open(tmp_path / f"profile-rank-{rank}.json", "w") as f:
                json.dump(_fake_dump(rank, t0, offset), f)
            with open(tmp_path / f"timeline-rank-{rank}.json", "w") as f:
                # a runtime timeline fragment (open JSON array form)
                f.write(json.dumps([
                    {"ph": "B", "pid": 9, "tid": 3, "ts": t0 * 1e6,
                     "name": "ALLREDUCE"},
                    {"ph": "E", "pid": 9, "tid": 3,
                     "ts": (t0 + 0.01) * 1e6}])[:-1] + ",")
        out, n = profiler.merge_profile_dir(str(tmp_path))
        assert os.path.exists(out) and n > 0
        doc = json.load(open(out))  # valid JSON or this raises
        events = doc["traceEvents"]
        labels = {e["args"]["labels"] for e in events
                  if e.get("name") == "process_labels"}
        assert {"rank 0 steps", "rank 1 steps", "rank 0 timeline",
                "rank 1 timeline"} <= labels
        # per-lane timestamps are monotonic
        lanes = {}
        for e in events:
            if e.get("ph") == "M" or not isinstance(
                    e.get("ts"), (int, float)):
                continue
            key = (e.get("pid"), e.get("tid"))
            assert e["ts"] >= lanes.get(key, float("-inf")), key
            lanes[key] = e["ts"]
        # rank 1's events were shifted by its clock offset (+2.5 s)
        r0 = [e["ts"] for e in events
              if e.get("name") == "step 0" and e.get("ph") == "X"]
        assert max(r0) - min(r0) == pytest.approx(2.5e6)

    def test_step_report_names_slowest_rank_and_phase(self, tmp_path):
        from horovod_tpu import profiler

        fast = _fake_dump(0, 1700000000.0)
        slow = _fake_dump(1, 1700000000.0)
        for s in slow["steps"]:
            s["wall_seconds"] = 0.2
            s["phases"] = {"host": 0.01, "compute": 0.02,
                           "exposed_comm": 0.16, "optimizer": 0.01}
        report = profiler.format_step_report([fast, slow])
        assert "slowest: rank 1" in report
        assert "dominant phase: exposed_comm" in report

    def test_profile_report_cli(self, tmp_path, capsys):
        from horovod_tpu.run.run import run_commandline

        with open(tmp_path / "profile-rank-0.json", "w") as f:
            json.dump(_fake_dump(0, 1700000000.0), f)
        assert run_commandline(["--profile-report", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "step-time report" in out
        assert run_commandline(
            ["--profile-report", str(tmp_path / "empty")]) == 1


class TestKnobs:
    def test_defaults(self, monkeypatch):
        from horovod_tpu.utils import env

        for knob in (env.HOROVOD_PROFILE, env.HOROVOD_PROFILE_DIR,
                     env.HOROVOD_PROFILE_HISTORY, env.HOROVOD_PROFILE_JAX):
            monkeypatch.delenv(knob, raising=False)
        cfg = env.Config.from_env()
        assert cfg.profile is False
        assert cfg.profile_dir == ""
        assert cfg.profile_history == env.DEFAULT_PROFILE_HISTORY
        assert cfg.profile_jax is False

    def test_profile_dir_implies_enable(self, monkeypatch):
        from horovod_tpu.utils import env

        monkeypatch.delenv(env.HOROVOD_PROFILE, raising=False)
        monkeypatch.setenv(env.HOROVOD_PROFILE_DIR, "/tmp/prof")
        cfg = env.Config.from_env()
        assert cfg.profile is True
        assert cfg.profile_dir == "/tmp/prof"


# ---------------------------------------------------------------------------
# 2-rank end-to-end merge over the real transport
# ---------------------------------------------------------------------------

def _native_built():
    from horovod_tpu.runtime.native import native_built

    return native_built()


@pytest.mark.skipif(not _native_built(),
                    reason="native transport not built")
def test_two_rank_profile_merge(tmp_path):
    """Acceptance: a 2-rank run with HOROVOD_PROFILE_DIR leaves per-rank
    dumps + timelines that merge into ONE Perfetto-loadable trace with
    both ranks' runtime spans and step markers on a common clock, and the
    cross-rank step report covers both ranks."""
    from horovod_tpu import profiler
    from horovod_tpu.run.rendezvous import RendezvousServer

    profile_dir = tmp_path / "profile"
    os.makedirs(profile_dir)
    rendezvous = RendezvousServer(host="127.0.0.1")
    http_port = rendezvous.start()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        socket_port = s.getsockname()[1]
    world, procs, outs = 2, [], []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "15",
                "HOROVOD_PROFILE_DIR": str(profile_dir),
                "HOROVOD_TIMELINE": str(
                    profile_dir / f"timeline-rank-{rank}.json"),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        rendezvous.stop()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "DONE" in out, out

    dumps = profiler.load_dumps(str(profile_dir))
    assert sorted(d["launch_rank"] for d in dumps) == [0, 1]
    out_path, n_events = profiler.merge_profile_dir(str(profile_dir))
    assert n_events > 0
    doc = json.load(open(out_path))
    events = doc["traceEvents"]
    labels = {e["args"]["labels"] for e in events
              if e.get("name") == "process_labels"}
    assert {"rank 0 steps", "rank 1 steps"} <= labels
    assert {"rank 0 timeline", "rank 1 timeline"} <= labels, labels
    # step markers from BOTH ranks made it onto the common clock
    step_ranks = {lbl for lbl in labels if lbl.endswith("steps")}
    assert len(step_ranks) == 2
    report = profiler.format_step_report(dumps)
    assert "2 ranks" in report
    assert "rank 0:" in report and "rank 1:" in report
    assert "slowest: rank" in report
