"""Resilience-layer tests: RetryPolicy schedule/classification, the
chaos grammar and injection seam, generation fencing, and the rendezvous
store under injected kv_outage windows, concurrent writers, and TTL
expiry (ISSUE 8 satellite coverage)."""

import random
import socket
import threading
import time
from urllib.error import HTTPError, URLError

import pytest

from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer
from horovod_tpu.utils import resilience


@pytest.fixture
def chaos_env(monkeypatch):
    """Arm HOROVOD_FAULT_INJECT for one test and disarm afterwards."""

    def arm(spec, rank="0"):
        monkeypatch.setenv("HOROVOD_FAULT_INJECT", spec)
        monkeypatch.setenv("HOROVOD_RANK", rank)
        resilience.reload_chaos()

    yield arm
    monkeypatch.delenv("HOROVOD_FAULT_INJECT", raising=False)
    resilience.reload_chaos()


def _retries(transport):
    return _metrics().counter(
        "horovod_net_retries_total", "", labelnames=("transport",)
    ).labels(transport=transport).value


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_full_jitter_bounds():
    policy = resilience.RetryPolicy(
        base_delay=0.1, max_delay=2.0, rng=random.Random(7))
    for attempt in range(1, 12):
        cap = min(2.0, 0.1 * 2 ** (attempt - 1))
        for _ in range(50):
            d = policy.delay_for(attempt)
            assert 0.0 <= d <= cap
    # the cap actually binds: large attempts never exceed max_delay
    assert max(policy.delay_for(30) for _ in range(100)) <= 2.0


def test_call_retries_transients_then_succeeds():
    sleeps = []
    policy = resilience.RetryPolicy(
        transport="t1", max_retries=5, base_delay=0.01,
        sleep=sleeps.append, rng=random.Random(3))
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("boom")
        return "ok"

    before = _retries("t1")
    assert policy.call(flaky, phase="unit") == "ok"
    assert len(calls) == 3
    assert len(sleeps) == 2
    assert _retries("t1") - before == 2


def test_call_nonretryable_passes_through():
    sleeps = []
    policy = resilience.RetryPolicy(sleep=sleeps.append)

    def bad():
        raise ValueError("not a transport error")

    with pytest.raises(ValueError):
        policy.call(bad, phase="unit")
    assert sleeps == []  # no retry, no backoff


def test_call_exhausts_attempts_and_reraises():
    sleeps = []
    policy = resilience.RetryPolicy(
        max_retries=2, base_delay=0.01, sleep=sleeps.append,
        rng=random.Random(1))
    with pytest.raises(ConnectionResetError):
        policy.call(lambda: (_ for _ in ()).throw(
            ConnectionResetError("always")), phase="unit")
    assert len(sleeps) == 2  # max_retries backoffs, then re-raise


def test_call_deadline_exhaustion():
    # a deadline of 0 leaves no room for even one backoff
    policy = resilience.RetryPolicy(
        max_retries=50, base_delay=0.5, sleep=lambda d: None,
        rng=random.Random(2))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        policy.call(lambda: (_ for _ in ()).throw(TimeoutError("slow")),
                    phase="unit", deadline=0.0)
    assert time.monotonic() - t0 < 1.0


def test_classification():
    assert resilience.is_retryable(ConnectionResetError())
    assert resilience.is_retryable(socket.timeout())
    assert resilience.is_retryable(TimeoutError())
    assert resilience.is_retryable(URLError("refused"))
    assert resilience.is_retryable(resilience.ChaosError())
    for code in resilience.RETRYABLE_HTTP_CODES:
        assert resilience.is_retryable(
            HTTPError("http://x", code, "err", None, None))
    # 404 is the rendezvous key-absent protocol signal, never retried
    assert not resilience.is_retryable(
        HTTPError("http://x", 404, "missing", None, None))
    assert not resilience.is_retryable(
        HTTPError("http://x", 403, "denied", None, None))
    assert not resilience.is_retryable(KeyError("x"))
    assert not resilience.is_retryable(ValueError("x"))


def test_from_env_overrides(monkeypatch):
    monkeypatch.setenv("HOROVOD_NET_MAX_RETRIES", "9")
    monkeypatch.setenv("HOROVOD_NET_BACKOFF_BASE_SECONDS", "0.5")
    policy = resilience.RetryPolicy.from_env("kv", deadline=3.0)
    assert policy.max_retries == 9
    assert policy.base_delay == 0.5
    assert policy.deadline == 3.0
    assert policy.transport == "kv"


# ---------------------------------------------------------------------------
# chaos grammar + injection seam
# ---------------------------------------------------------------------------

def test_parse_net_faults_grammar():
    faults = resilience.parse_net_faults(
        "partition:1:30:after=4; kv_outage:5:on=reform; "
        "flaky:0.3:rank=2:seconds=10; netdelay:25")
    kinds = [f.kind for f in faults]
    assert kinds == ["partition", "kv_outage", "flaky", "netdelay"]
    part, outage, flaky, delay = faults
    assert (part.rank, part.seconds, part.after) == (1, 30.0, 4.0)
    assert (outage.seconds, outage.on) == (5.0, "reform")
    assert (flaky.prob, flaky.rank, flaky.seconds) == (0.3, 2, 10.0)
    assert delay.delay_ms == 25.0 and delay.rank is None


def test_parse_net_faults_skips_process_clauses():
    faults = resilience.parse_net_faults(
        "kill:rank=1:step=3;flaky:0.5")
    assert [f.kind for f in faults] == ["flaky"]
    assert resilience.is_net_clause("partition:0")
    assert not resilience.is_net_clause("kill:rank=1:step=3")


def test_parse_net_faults_rejects_malformed():
    with pytest.raises(ValueError):
        resilience.parse_net_faults("partition")  # missing rank
    with pytest.raises(ValueError):
        resilience.parse_net_faults("flaky:notaprob")
    with pytest.raises(ValueError):
        resilience.parse_net_faults("netdelay:10:bogus=1")


def test_process_fault_parser_skips_net_clauses(chaos_env):
    from horovod_tpu.elastic import fault_inject

    chaos_env("kv_outage:5:on=reform;kill:rank=1:step=3")
    spec = fault_inject.spec_from_env()
    assert spec is not None
    assert (spec.action, spec.rank, spec.step) == ("kill", 1, 3)


def test_inject_flaky_raises_chaos_error(chaos_env):
    chaos_env("flaky:1.0")
    with pytest.raises(resilience.ChaosError):
        resilience.inject("kv", "unit")


def test_inject_flaky_targets_launch_rank_only(chaos_env):
    chaos_env("flaky:1.0:rank=3", rank="0")
    resilience.inject("kv", "unit")  # not rank 3: no-op


def test_inject_netdelay_sleeps(chaos_env):
    chaos_env("netdelay:80")
    t0 = time.monotonic()
    resilience.inject("ctrl", "unit")
    assert time.monotonic() - t0 >= 0.07


def test_parse_netdelay_hop_cross():
    (delay,) = resilience.parse_net_faults("netdelay:5:hop=cross")
    assert (delay.kind, delay.delay_ms, delay.hop) == ("netdelay", 5.0,
                                                       "cross")
    with pytest.raises(ValueError):
        resilience.parse_net_faults("netdelay:5:hop=intra")


def test_inject_netdelay_hop_cross_scales_with_crossings(chaos_env):
    chaos_env("netdelay:40:hop=cross")
    # a seam off the slow link (or one that doesn't model topology at
    # all) declares no crossings and must not pay the delay
    t0 = time.monotonic()
    resilience.inject("hier_intra", "reducescatter", crossings=0)
    resilience.inject("ctrl", "unit")
    assert time.monotonic() - t0 < 0.03
    # the cross hop pays per declared crossing: 2(G-1) = 2 at G=2
    t0 = time.monotonic()
    resilience.inject("hier_cross", "allreduce", crossings=2)
    assert time.monotonic() - t0 >= 0.07


def test_inject_partition_blocks_window(chaos_env):
    chaos_env("partition:0:0.3")
    t0 = time.monotonic()
    resilience.inject("ctrl", "unit")  # sleeps out the remaining window
    assert time.monotonic() - t0 >= 0.25


def test_generation_fence_roundtrip():
    old = resilience.current_generation()
    try:
        resilience.set_generation(old + 5)
        assert resilience.current_generation() == old + 5
    finally:
        resilience.set_generation(old)


def test_collective_timeout_knob(monkeypatch):
    assert resilience.collective_timeout() == 0.0
    monkeypatch.setenv("HOROVOD_COLLECTIVE_TIMEOUT", "7.5")
    assert resilience.collective_timeout() == 7.5


# ---------------------------------------------------------------------------
# rendezvous under chaos / load
# ---------------------------------------------------------------------------

def _fast_retry(**kw):
    kw.setdefault("max_retries", 30)
    kw.setdefault("base_delay", 0.05)
    kw.setdefault("max_delay", 0.15)
    kw.setdefault("attempt_timeout", 5.0)
    return resilience.RetryPolicy(transport="kv", **kw)


def test_kv_outage_bridged_by_client_retry(chaos_env, monkeypatch):
    """A timer-armed kv_outage shorter than the op deadline is invisible
    to callers — and the retries are visible in the metrics."""
    chaos_env("kv_outage:0.6")
    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        # disarm client-side chaos parsing (kv_outage is server-side
        # anyway, but keep the seam quiet for determinism)
        monkeypatch.delenv("HOROVOD_FAULT_INJECT", raising=False)
        resilience.reload_chaos()
        server.put("global", "answer", b"42")
        client = KVStoreClient("127.0.0.1", port, timeout=10,
                               retry=_fast_retry())
        before = _retries("kv")
        t0 = time.monotonic()
        assert client.get("answer") == b"42"
        assert time.monotonic() - t0 >= 0.4  # sat out most of the outage
        assert _retries("kv") - before > 0
        # set/finish also retry through the tail of an outage
        client.set("post", b"v")
        assert server.get("global", "post") == b"v"
    finally:
        server.stop()


def test_kv_outage_reform_armed_by_elastic_scope(chaos_env, monkeypatch):
    """An on=reform outage stays dormant until elastic.g* traffic."""
    chaos_env("kv_outage:0.5:on=reform")
    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        monkeypatch.delenv("HOROVOD_FAULT_INJECT", raising=False)
        resilience.reload_chaos()
        client = KVStoreClient("127.0.0.1", port, timeout=10,
                               retry=_fast_retry())
        # non-elastic traffic does NOT arm the window
        client.set("k", b"v")
        assert server._httpd.chaos_outage_start is None
        # first per-generation registration arms it and eats the 503s
        client.set("member.0", b"uid", scope="elastic.g1")
        assert server._httpd.chaos_outage_start is not None
        assert server.get("elastic.g1", "member.0") == b"uid"
    finally:
        server.stop()


def test_rendezvous_concurrent_writers():
    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        client = KVStoreClient("127.0.0.1", port, timeout=10)
        errors = []

        def writer(i):
            try:
                c = KVStoreClient("127.0.0.1", port, timeout=10)
                for j in range(5):
                    c.set(f"w{i}.{j}", f"{i}:{j}".encode())
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        keys = set(client.keys("global"))
        assert {f"w{i}.{j}" for i in range(8) for j in range(5)} <= keys
        assert client.get("w3.4", wait=False) == b"3:4"
    finally:
        server.stop()


def test_heartbeat_ttl_expiry_during_outage(chaos_env, monkeypatch):
    """TTL expiry is wall-clock: a beat that dies during an outage window
    reads as lost once the window lifts."""
    chaos_env("kv_outage:0.4")
    server = RendezvousServer("127.0.0.1", heartbeat_ttl=0.3)
    port = server.start()
    try:
        monkeypatch.delenv("HOROVOD_FAULT_INJECT", raising=False)
        resilience.reload_chaos()
        server.put("heartbeat", "0-123", b"beat")
        assert server.live_keys("heartbeat") == ["0-123"]
        time.sleep(0.5)  # outage AND ttl both elapse
        client = KVStoreClient("127.0.0.1", port, timeout=5,
                               retry=_fast_retry())
        assert client.keys("heartbeat") == []
        with pytest.raises(KeyError):
            client.get("0-123", scope="heartbeat", wait=False)
    finally:
        server.stop()


def test_every_http_op_has_default_socket_timeout():
    """A server that accepts but never answers can only hold an op for
    the per-attempt timeout, not forever (ISSUE 8 satellite)."""
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    port = lst.getsockname()[1]
    try:
        policy = resilience.RetryPolicy(
            transport="kv", max_retries=1, base_delay=0.01,
            attempt_timeout=0.3)
        client = KVStoreClient("127.0.0.1", port, timeout=1,
                               retry=policy)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            client.set("k", b"v")
        assert time.monotonic() - t0 < 5.0
    finally:
        lst.close()


def test_get_retries_bounded_by_op_deadline(chaos_env, monkeypatch):
    """During an outage longer than get()'s own deadline the op fails
    with the familiar TimeoutError/HTTPError, not an infinite retry."""
    chaos_env("kv_outage:30")
    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        monkeypatch.delenv("HOROVOD_FAULT_INJECT", raising=False)
        resilience.reload_chaos()
        client = KVStoreClient("127.0.0.1", port, timeout=0.8,
                               retry=_fast_retry())
        t0 = time.monotonic()
        with pytest.raises((TimeoutError, HTTPError)):
            client.get("never")
        assert time.monotonic() - t0 < 10.0
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# broadcast_object stall typing (satellite: runtime/coordination.py)
# ---------------------------------------------------------------------------

def test_broadcast_object_timeout_is_typed(monkeypatch):
    from horovod_tpu.exceptions import WorkerStallError
    from horovod_tpu.runtime import coordination

    class _StuckClient:
        def key_value_set(self, key, value):
            pass

        def blocking_key_value_get(self, key, timeout_ms):
            raise RuntimeError("Deadline Exceeded waiting for key")

    class _State:
        local_size = 1

    monkeypatch.setattr(coordination, "_kv_client",
                        lambda: _StuckClient())
    from horovod_tpu.core import state as state_mod

    monkeypatch.setattr(state_mod, "global_state", lambda: _State())
    with pytest.raises(WorkerStallError) as err:
        coordination.broadcast_object({"x": 1}, name="unit_bcast",
                                      timeout_ms=200)
    assert "unit_bcast" in str(err.value)
    assert "root process 0" in str(err.value)
