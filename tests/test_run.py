"""Launcher tests — slot allocation, config translation, wire security,
rendezvous, services, and end-to-end tpurun fan-out.

Mirrors the reference's launcher unit tests (reference: test/test_run.py:
53-216 — pure-Python arg/config/env translation, no cluster) plus
end-to-end local fan-out the reference exercises in its Docker images.
"""

import io
import json
import os
import sys
import tempfile
import textwrap

import pytest

from horovod_tpu.run import config_parser, hosts, launcher, service, util
from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer
from horovod_tpu.run.run import check_build, parse_args, run_commandline


# ---------------------------------------------------------------------------
# host parsing / slot allocation (reference: gloo_run.py:56-114 semantics)
# ---------------------------------------------------------------------------

def test_parse_hosts():
    infos = hosts.parse_hosts("h1:2, h2:4,h3")
    assert [(h.hostname, h.slots) for h in infos] == [
        ("h1", 2), ("h2", 4), ("h3", 1)]


def test_parse_hostfile(tmp_path):
    path = tmp_path / "hostfile"
    path.write_text("h1 slots=2\n# comment\nh2 slots=4\nh3\n")
    infos = hosts.parse_hostfile(str(path))
    assert [(h.hostname, h.slots) for h in infos] == [
        ("h1", 2), ("h2", 4), ("h3", 1)]


def test_allocate_uniform():
    slots = hosts.allocate(hosts.parse_hosts("h1:2,h2:2"), 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.hostname for s in slots] == ["h1", "h1", "h2", "h2"]
    assert [s.local_rank for s in slots] == [0, 1, 0, 1]
    assert all(s.local_size == 2 for s in slots)
    assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
    assert all(s.cross_size == 2 for s in slots)


def test_allocate_heterogeneous():
    # h1:3, h2:1 → local sizes differ; cross_size depends on local_rank
    slots = hosts.allocate(hosts.parse_hosts("h1:3,h2:1"), 4)
    by_rank = {s.rank: s for s in slots}
    assert by_rank[3].hostname == "h2"
    assert by_rank[3].local_rank == 0 and by_rank[3].local_size == 1
    # local_rank 0 exists on both hosts
    assert by_rank[0].cross_size == 2 and by_rank[3].cross_size == 2
    # local_rank 1 and 2 exist only on h1
    assert by_rank[1].cross_size == 1 and by_rank[2].cross_size == 1
    assert by_rank[3].cross_rank == 1


def test_allocate_truncates_to_np():
    slots = hosts.allocate(hosts.parse_hosts("h1:4,h2:4"), 3)
    assert len(slots) == 3
    assert all(s.hostname == "h1" for s in slots)
    assert slots[0].local_size == 3  # only 3 used on h1


def test_allocate_oversubscribe_raises():
    with pytest.raises(ValueError):
        hosts.allocate(hosts.parse_hosts("h1:2"), 3)


def test_slot_env_contract():
    slot = hosts.allocate(hosts.parse_hosts("h1:2,h2:2"), 4)[2]
    env = slot.to_env()
    assert env["HOROVOD_RANK"] == "2"
    assert env["HOROVOD_SIZE"] == "4"
    assert env["HOROVOD_LOCAL_RANK"] == "0"
    assert env["HOROVOD_CROSS_RANK"] == "1"


# ---------------------------------------------------------------------------
# CLI args / config file / env translation (reference: test_run.py:53-216)
# ---------------------------------------------------------------------------

def test_args_to_env():
    args = parse_args(
        ["-np", "2", "--fusion-threshold-mb", "32", "--cycle-time-ms", "7.5",
         "--cache-capacity", "2048", "--timeline-filename", "/tmp/t.json",
         "--autotune", "--log-level", "debug", "python", "train.py"])
    env = config_parser.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "7.5"
    assert env["HOROVOD_CACHE_CAPACITY"] == "2048"
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert env["HOROVOD_LOG_LEVEL"] == "debug"
    assert args.command == ["python", "train.py"]


def test_config_file_and_cli_precedence(tmp_path):
    config = tmp_path / "cfg.yaml"
    config.write_text(textwrap.dedent("""\
        params:
            fusion_threshold_mb: 16
            cycle_time_ms: 3.0
        timeline:
            filename: /tmp/from_config.json
        stall_check:
            enabled: false
        """))
    # --cycle-time-ms on the CLI beats the config file
    args = parse_args(
        ["-np", "2", "--config-file", str(config),
         "--cycle-time-ms", "9.0", "cmd"])
    env = config_parser.env_from_args(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(16 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "9.0"
    assert env["HOROVOD_TIMELINE"] == "/tmp/from_config.json"
    assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        parse_args(["-np", "2", "--cycle-time-ms", "-1", "cmd"])


def test_check_build_reports_capabilities():
    out = io.StringIO()
    check_build(out)
    text = out.getvalue()
    assert "JAX" in text and "XLA collectives" in text
    assert "[X] XLA (in-jit SPMD)" in text


# ---------------------------------------------------------------------------
# wire security (reference: network.py:50-84 HMAC framing)
# ---------------------------------------------------------------------------

def test_wire_roundtrip_and_tamper_rejection():
    key = util.make_secret_key()
    wire = util.Wire(key)
    buf = io.BytesIO()
    wire.write({"hello": [1, 2, 3]}, buf)
    buf.seek(0)
    assert wire.read(buf) == {"hello": [1, 2, 3]}

    # flip a payload byte → HMAC must fail before unpickling
    raw = bytearray(buf.getvalue())
    raw[-1] ^= 0xFF
    with pytest.raises(IOError):
        wire.read(io.BytesIO(bytes(raw)))

    # wrong key → reject
    with pytest.raises(IOError):
        util.Wire(util.make_secret_key()).read(io.BytesIO(buf.getvalue()))


def test_secret_encode_roundtrip():
    key = util.make_secret_key()
    assert util.decode_secret(util.encode_secret(key)) == key


# ---------------------------------------------------------------------------
# rendezvous KV store (reference: run/rendezvous/http_server.py)
# ---------------------------------------------------------------------------

def test_rendezvous_put_get_finish():
    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        client = KVStoreClient("127.0.0.1", port, scope="global", timeout=5)
        with pytest.raises(KeyError):
            client.get("addr", wait=False)
        client.set("addr", b"1.2.3.4:99")
        assert client.get("addr") == b"1.2.3.4:99"
        # scoped keys are independent (global vs local_0)
        client.set("addr", b"other", scope="local_0")
        assert client.get("addr", scope="local_0") == b"other"
        assert client.get("addr") == b"1.2.3.4:99"
        client.finish("addr")
        assert server.finished_keys("global") == {"addr"}
    finally:
        server.stop()


def test_rendezvous_port_collision_retry():
    """An explicit port held by a dying server is retried with backoff
    instead of failing the launch (port=0 never retries)."""
    import threading

    holder = RendezvousServer("127.0.0.1")
    port = holder.start()
    # while the holder is alive, a no-retry bind must fail fast
    with pytest.raises(OSError):
        RendezvousServer("127.0.0.1", port=port, bind_retries=0)
    releaser = threading.Timer(0.5, holder.stop)
    releaser.start()
    try:
        server = RendezvousServer("127.0.0.1", port=port, bind_retries=25)
        assert server.start() == port
        server.stop()
    finally:
        releaser.join()


def test_rendezvous_waits_for_publication():
    import threading
    import time as time_mod

    server = RendezvousServer("127.0.0.1")
    port = server.start()
    try:
        client = KVStoreClient("127.0.0.1", port, timeout=10)

        def publish():
            time_mod.sleep(0.3)
            client.set("late", b"v")

        t = threading.Thread(target=publish)
        t.start()
        assert client.get("late") == b"v"  # long-polls until published
        t.join()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# driver/task services (reference: run/common/service/*)
# ---------------------------------------------------------------------------

def test_driver_task_registration_and_command():
    key = util.make_secret_key()
    driver = service.DriverService(key, num_tasks=2)
    tasks = [service.TaskService(key, index=i) for i in range(2)]
    try:
        for t in tasks:
            t.register(("127.0.0.1", driver.port), key)
        driver.wait_for_initial_registration(util.Timeout(10, "registration"))
        addrs = driver.task_addresses()
        assert set(addrs) == {0, 1}

        # driver asks task 0 to run a command, polls its exit code
        client = service.ServiceClient(("127.0.0.1", tasks[0].port), key)
        with tempfile.TemporaryDirectory() as d:
            marker = os.path.join(d, "ran")
            client.call(service.RunCommandRequest(
                f"touch {marker}", dict(os.environ)))
            deadline = 50
            code = None
            while deadline and code is None:
                code = client.call(service.CommandExitCodeRequest())
                deadline -= 1
                if code is None:
                    import time as time_mod
                    time_mod.sleep(0.1)
            assert code == 0
            assert os.path.exists(marker)
    finally:
        driver.shutdown()
        for t in tasks:
            t.shutdown()


def test_wrong_key_rejected_by_service():
    key = util.make_secret_key()
    driver = service.DriverService(key, num_tasks=1)
    try:
        bad_client = service.ServiceClient(
            ("127.0.0.1", driver.port), util.make_secret_key())
        with pytest.raises((EOFError, IOError, RuntimeError)):
            bad_client.call(service.PingRequest())
    finally:
        driver.shutdown()


# ---------------------------------------------------------------------------
# end-to-end local fan-out
# ---------------------------------------------------------------------------

SCRIPT = textwrap.dedent("""\
    import json, os, sys
    keys = ["HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
            "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR", "HOROVOD_GLOO_RENDEZVOUS_PORT",
            "HOROVOD_CYCLE_TIME"]
    out = {k: os.environ.get(k) for k in keys}
    path = os.path.join(os.environ["TEST_OUT_DIR"],
                        "rank_%s.json" % os.environ["HOROVOD_RANK"])
    with open(path, "w") as f:
        json.dump(out, f)
""")


def test_tpurun_local_fanout(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(SCRIPT)
    os.environ["TEST_OUT_DIR"] = str(tmp_path)
    try:
        code = run_commandline(
            ["-np", "3", "--no-jax-distributed", "--cycle-time-ms", "2.5",
             sys.executable, str(script)])
    finally:
        os.environ.pop("TEST_OUT_DIR", None)
    assert code == 0
    ranks = []
    for r in range(3):
        with open(tmp_path / f"rank_{r}.json") as f:
            env = json.load(f)
        ranks.append(int(env["HOROVOD_RANK"]))
        assert env["HOROVOD_SIZE"] == "3"
        assert env["HOROVOD_LOCAL_SIZE"] == "3"
        assert env["HOROVOD_CROSS_SIZE"] == "1"
        assert env["HOROVOD_CYCLE_TIME"] == "2.5"
        assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] is not None
    assert sorted(ranks) == [0, 1, 2]


def test_tpurun_output_capture(tmp_path):
    outdir = tmp_path / "logs"
    code = run_commandline(
        ["-np", "2", "--no-jax-distributed",
         "--output-filename", str(outdir),
         sys.executable, "-c", "import os; print('hello from', os.environ['HOROVOD_RANK'])"])
    assert code == 0
    for r in range(2):
        content = (outdir / f"rank.{r}" / "stdout").read_text()
        assert f"hello from {r}" in content


def test_tpurun_failure_propagates(tmp_path):
    # rank 1 exits non-zero; job must fail (reference: gloo_run.py:256-262)
    script = tmp_path / "fail.py"
    script.write_text(textwrap.dedent("""\
        import os, sys, time
        if os.environ["HOROVOD_RANK"] == "1":
            sys.exit(3)
        time.sleep(30)  # must be killed, not run 30s
    """))
    import time as time_mod
    t0 = time_mod.monotonic()
    code = run_commandline(
        ["-np", "2", "--no-jax-distributed", sys.executable, str(script)])
    elapsed = time_mod.monotonic() - t0
    assert code == 3
    assert elapsed < 25  # surviving rank was torn down


def test_tpurun_no_command_errors():
    assert run_commandline(["-np", "2"]) == 2


def _run_mp_worker(monkeypatch, scenario, extra_flags=()):
    """tpurun-launch mp_worker.py ranks (workers don't want the parent's
    8-fake-device XLA_FLAGS)."""
    from horovod_tpu.runtime.native import native_built

    if not native_built():
        pytest.skip("native transport not built")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mp_worker.py")
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    return run_commandline(
        ["-np", "2", *extra_flags, sys.executable, worker, scenario])


# ---------------------------------------------------------------------------
# NIC discovery (reference: run/run.py:195-265 ring probe)
# ---------------------------------------------------------------------------

def test_nic_discovery_filters_unroutable(monkeypatch):
    """The ring probe drops candidate addresses nothing can reach and the
    driver address is one tasks actually used — mocked multi-NIC setup."""
    from horovod_tpu.run import discovery, service, util as run_util

    real_local_addresses = service.local_addresses

    def fake_local_addresses(port):
        # a dead NIC candidate first: TEST-NET-1, guaranteed unroutable
        return [("192.0.2.1", port)] + real_local_addresses(port)

    monkeypatch.setattr(service, "local_addresses", fake_local_addresses)
    key = run_util.make_secret_key()
    result = discovery.discover(
        ["localhost", "localhost"], key, is_local=lambda h: True,
        timeout=60.0)
    assert result.driver_addr and result.driver_addr != "192.0.2.1"
    assert set(result.host_routable) == {0, 1}
    for idx, addrs in result.host_routable.items():
        assert addrs, f"host {idx} has no routable address"
        assert all(ip != "192.0.2.1" for ip, _ in addrs)


def test_nic_discovery_raises_when_unreachable(monkeypatch):
    """No routable address -> a clear error naming the host (reference
    raises the same way, run/run.py:253-262)."""
    from horovod_tpu.run import discovery, service, util as run_util

    # deny only the ring probes (registration still works): the same
    # code path as all-dead NICs between hosts
    real_handle = service.TaskService._handle

    def deny_ring_probe(self, req):
        if isinstance(req, service.ProbeAddressesRequest) and req.addresses:
            return service.OkResponse([])
        return real_handle(self, req)

    monkeypatch.setattr(service.TaskService, "_handle", deny_ring_probe)
    key = run_util.make_secret_key()
    with pytest.raises(RuntimeError, match="no routable address"):
        discovery.discover(["localhost", "localhost"], key,
                           is_local=lambda h: True, timeout=30.0)


def test_ring_probe_runs_concurrently(monkeypatch):
    """32 mocked hosts, each dial costing a fixed delay: the probe phase
    must take ~one probe round (concurrent), not 32 serial rounds — the
    reference launches all task probes at once (run/run.py:195-265)."""
    import time

    from horovod_tpu.run import discovery, util as run_util

    n, dial_delay = 32, 0.2
    task_addresses = {i: [(f"10.0.0.{i}", 9000 + i)] for i in range(n)}

    class FakeClient:
        def __init__(self, addrs):
            self.addrs = addrs

        def call(self, request, timeout=None):
            time.sleep(dial_delay)  # the task->successor probe
            return request.addresses

    def fake_client_for(addresses, key, probe_timeout=3.0):
        time.sleep(dial_delay)  # the driver->task dial
        return FakeClient(addresses)

    monkeypatch.setattr(discovery, "_client_for", fake_client_for)
    key = run_util.make_secret_key()
    t0 = time.perf_counter()
    routable = discovery._ring_probe(task_addresses, key, probe_timeout=1.0)
    wall = time.perf_counter() - t0
    assert set(routable) == set(range(n))
    for i in range(n):
        assert routable[i] == [tuple(a) for a in task_addresses[i]]
    # serial would be n * 2 * dial_delay = 12.8s; concurrent is ~2 dials.
    # Generous bound (4 rounds) for a loaded 1-core CI box.
    assert wall < 4 * 2 * dial_delay, f"probe phase not concurrent: {wall:.2f}s"


def test_task_agent_key_over_stdin(monkeypatch, capsys):
    """--key-stdin reads the HMAC key from stdin (never the command line /
    process environment); a bad driver address makes registration fail
    fast but proves the key parse happened."""
    from horovod_tpu.run import task_agent

    monkeypatch.delenv("HOROVOD_TASK_KEY", raising=False)
    monkeypatch.setattr("sys.stdin", io.StringIO("a1b2c3d4\n"))
    # key parse succeeds (no KeyError on the absent env var); registration
    # then times out against the dead driver address
    with pytest.raises(TimeoutError):
        task_agent.main(["0", "1", "127.0.0.1:1", "0.2", "--key-stdin"])
    # and without --key-stdin the env fallback still applies
    monkeypatch.setenv("HOROVOD_TASK_KEY", "a1b2c3d4")
    with pytest.raises(TimeoutError):
        task_agent.main(["0", "1", "127.0.0.1:1", "0.2"])


def test_tpurun_forced_nic_discovery(monkeypatch):
    """End-to-end: 2-process localhost launch with discovery forced on
    feeds the proven driver address into the rendezvous env."""
    monkeypatch.setenv("HOROVOD_NIC_DISCOVERY", "1")
    assert _run_mp_worker(
        monkeypatch, "collectives", ["--no-jax-distributed"]) == 0


def test_tpurun_end_to_end_collective(monkeypatch):
    """tpurun-launched workers form a world and allreduce through the
    socket controller — the full launcher→init→collective path the
    reference exercises via `horovodrun -np 2 pytest ...`."""
    assert _run_mp_worker(
        monkeypatch, "collectives", ["--no-jax-distributed"]) == 0


def test_tpurun_large_tensor_ring(monkeypatch):
    """32 MB fused buffer through the host ring — regression test for the
    full-duplex exchange (a blocking ring deadlocks once chunks exceed
    kernel socket buffering)."""
    assert _run_mp_worker(
        monkeypatch, "large_allreduce", ["--no-jax-distributed"]) == 0


def test_tpurun_autotune_sync(monkeypatch):
    """--autotune: coordinator tunes, workers apply the per-cycle param
    broadcast; the job converges and stays numerically correct."""
    assert _run_mp_worker(
        monkeypatch, "autotune",
        ["--no-jax-distributed", "--autotune",
         "--autotune-warmup-samples", "0",
         "--autotune-steps-per-sample", "1",
         "--autotune-bayes-opt-max-samples", "2"]) == 0


def test_tpurun_spmd_global_mesh(monkeypatch):
    """Default tpurun mode: jax.distributed global mesh; the enqueue
    runtime's allreduce rides XLA collectives over the mesh (ICI analogue),
    with the socket net as control plane only."""
    import jax

    if jax.default_backend() == "cpu":
        pytest.skip(
            "CPU backend does not implement multiprocess XLA computations")
    assert _run_mp_worker(monkeypatch, "spmd_allreduce") == 0


def test_safe_exec_kills_process_tree():
    import threading
    import time as time_mod

    event = threading.Event()
    results = {}

    def run():
        results["code"] = util.execute(
            f"{sys.executable} -c 'import time; time.sleep(60)'",
            events=[event], prefix_output=False)

    t = threading.Thread(target=run)
    t.start()
    time_mod.sleep(0.5)
    event.set()
    t.join(timeout=20)
    assert not t.is_alive()
    assert results["code"] != 0


# ---------------------------------------------------------------------------
# launch backends (reference: the gloo-vs-mpirun selection seam,
# run/run.py:715-732 — here ssh vs gcloud TPU-VM)
# ---------------------------------------------------------------------------

def test_backend_selection(monkeypatch):
    from horovod_tpu.run import backends

    assert backends.make_backend(None).name == "ssh"
    assert backends.make_backend("gcloud-tpu-vm").name == "gcloud-tpu-vm"
    monkeypatch.setenv("HOROVOD_LAUNCH_BACKEND", "gcloud-tpu-vm")
    assert backends.make_backend(None).name == "gcloud-tpu-vm"
    assert backends.make_backend("ssh").name == "ssh"  # flag beats env
    with pytest.raises(ValueError, match="unknown launch backend"):
        backends.make_backend("mpirun")


def test_ssh_backend_commands():
    from horovod_tpu.run import backends

    b = backends.SSHBackend(ssh_port=2222)
    local = hosts.SlotInfo("localhost", rank=0, local_rank=0, local_size=2,
                           cross_rank=0, cross_size=1, size=2)
    remote = hosts.SlotInfo("worker-7", rank=1, local_rank=1, local_size=2,
                            cross_rank=0, cross_size=1, size=2)
    assert b.command_for_slot(local, "python train.py", {}) == \
        "python train.py"
    cmd = b.command_for_slot(
        remote, "python train.py",
        {"HOROVOD_RANK": "1", "SECRET_TOKEN": "x"})
    assert cmd.startswith("ssh ") and "-p 2222" in cmd and "worker-7" in cmd
    assert "HOROVOD_RANK=1" in cmd
    assert "SECRET_TOKEN" not in cmd  # only whitelisted prefixes exported


def test_gcloud_tpu_vm_backend_commands():
    from horovod_tpu.run import backends

    b = backends.GCloudTPUVMBackend(zone="us-central2-b", project="proj-1")
    slot = hosts.SlotInfo("my-pod", rank=5, local_rank=3, local_size=4,
                          cross_rank=1, cross_size=2, size=8)
    cmd = b.command_for_slot(slot, "python train.py",
                             {"HOROVOD_RANK": "5", "JAX_PLATFORMS": "tpu"})
    assert cmd.startswith("gcloud compute tpus tpu-vm ssh my-pod")
    assert "--worker=3" in cmd
    assert "--zone=us-central2-b" in cmd and "--project=proj-1" in cmd
    assert "HOROVOD_RANK=5" in cmd and "JAX_PLATFORMS=tpu" in cmd


def test_tpurun_gcloud_backend_skips_ssh_check(monkeypatch):
    """--launch-backend gcloud-tpu-vm must not plain-ssh TPU VM names; the
    constructed fan-out commands go through gcloud."""
    import horovod_tpu.run.run as run_mod
    from horovod_tpu.run import launcher as launcher_mod

    captured = {}

    def fake_launch_job(command, slots, **kw):
        captured["backend"] = kw.get("backend")
        captured["slots"] = slots
        return 0

    def boom(*a, **kw):
        raise AssertionError("ssh check must be skipped for gcloud backend")

    monkeypatch.setattr(run_mod.launcher, "launch_job", fake_launch_job)
    monkeypatch.setattr(run_mod, "check_all_hosts_ssh_successful", boom)
    rc = run_commandline(
        ["-np", "2", "-H", "pod-a:2", "--launch-backend", "gcloud-tpu-vm",
         "--gcloud-zone", "z", "python", "x.py"])
    assert rc == 0
    assert captured["backend"].name == "gcloud-tpu-vm"
