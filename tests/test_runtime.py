"""Dynamic enqueue runtime tests: queue, negotiation, cache, fusion,
handles, shutdown.

Mirrors the reference's coverage of the background runtime through the
bindings (reference: test/test_tensorflow.py fused-tensor test :152,
duplicate-name and error-path tests :314-384; test/test_torch.py async
handle tests) plus direct unit tests of the negotiation pieces.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.runtime import fusion, message as msg, types
from horovod_tpu.runtime.controller import (LocalController,
                                            construct_response)
from horovod_tpu.runtime.response_cache import (CacheCoordinator, CacheState,
                                                ResponseCache)
from horovod_tpu.runtime.tensor_queue import DuplicateNameError, TensorQueue


def _req(name, rank=0, rtype=types.ALLREDUCE, dtype="float32", shape=(4,),
         root=0, average=True, reduce_op=None):
    rop = reduce_op or ("average" if average else "sum")
    return msg.Request(rank, rtype, name, dtype, shape, root, rop)


class TestMessages:
    def test_request_roundtrip(self):
        r = _req("grad/layer_1/kernel", rank=3, shape=(128, 256), average=False)
        packed = r.pack()
        out, off = msg.Request.unpack(packed)
        assert out == r and off == len(packed)

    def test_response_roundtrip(self):
        r = msg.Response(types.ALLGATHER, ["a", "b"], tensor_sizes=[2, 3, 4])
        out, off = msg.Response.unpack(r.pack())
        assert out.response_type == types.ALLGATHER
        assert out.tensor_names == ["a", "b"]
        assert out.tensor_sizes == [2, 3, 4]

    def test_list_roundtrip(self):
        reqs = [_req(f"t{i}", rank=i) for i in range(5)]
        assert msg.unpack_request_list(msg.pack_request_list(reqs)) == reqs
        resps = [msg.Response(types.ERROR, ["x"], "boom")]
        out = msg.unpack_response_list(msg.pack_response_list(resps))
        assert out[0].error_message == "boom"


class TestTensorQueue:
    def test_duplicate_name_rejected(self):
        q = TensorQueue()
        e = types.TensorTableEntry(name="t", tensor=None)
        q.add(e, _req("t"))
        with pytest.raises(DuplicateNameError, match="same name"):
            q.add(types.TensorTableEntry(name="t", tensor=None), _req("t"))

    def test_priority_orders_popped_requests(self):
        """Higher priority drains first; enqueue order breaks ties
        (reference: mxnet ops' engine priority hint,
        horovod/mxnet/mpi_ops.py:52)."""
        q = TensorQueue()
        for name, prio in [("low", -1), ("first0", 0), ("high", 5),
                           ("second0", 0)]:
            q.add(types.TensorTableEntry(name=name, tensor=None,
                                         priority=prio), _req(name))
        assert [r.tensor_name for r in q.pop_requests()] == \
            ["high", "first0", "second0", "low"]
        assert q.pop_requests() == []

    def test_finalize_fires_callbacks(self):
        q = TensorQueue()
        statuses = []
        e = types.TensorTableEntry(
            name="t", tensor=None,
            callback=lambda s, out: statuses.append(s))
        q.add(e, _req("t"))
        q.finalize(types.Status.Aborted(types.SHUT_DOWN_ERROR))
        assert len(statuses) == 1 and not statuses[0].ok()
        assert len(q) == 0


class TestConstructResponse:
    """reference: ConstructResponse validation (controller.cc:320-522) and
    the error-path tests (test_tensorflow.py:314-384)."""

    def test_allreduce_ok(self):
        r = construct_response([_req("t", 0), _req("t", 1)])
        assert r.response_type == types.ALLREDUCE

    def test_allreduce_shape_mismatch(self):
        r = construct_response([_req("t", 0, shape=(4,)),
                                _req("t", 1, shape=(5,))])
        assert r.response_type == types.ERROR
        assert "shape" in r.error_message.lower()

    def test_dtype_mismatch(self):
        r = construct_response([_req("t", 0, dtype="float32"),
                                _req("t", 1, dtype="bfloat16")])
        assert r.response_type == types.ERROR
        assert "data type" in r.error_message.lower()

    def test_op_mismatch(self):
        r = construct_response([_req("t", 0, rtype=types.ALLREDUCE),
                                _req("t", 1, rtype=types.ALLGATHER)])
        assert r.response_type == types.ERROR

    def test_allgather_sizes_in_rank_order(self):
        r = construct_response([
            _req("t", 1, rtype=types.ALLGATHER, shape=(3, 2)),
            _req("t", 0, rtype=types.ALLGATHER, shape=(5, 2)),
        ])
        assert r.response_type == types.ALLGATHER
        assert r.tensor_sizes == [5, 3]

    def test_allgather_trailing_mismatch(self):
        r = construct_response([
            _req("t", 0, rtype=types.ALLGATHER, shape=(3, 2)),
            _req("t", 1, rtype=types.ALLGATHER, shape=(3, 4)),
        ])
        assert r.response_type == types.ERROR

    def test_broadcast_root_mismatch(self):
        r = construct_response([
            _req("t", 0, rtype=types.BROADCAST, root=0),
            _req("t", 1, rtype=types.BROADCAST, root=1),
        ])
        assert r.response_type == types.ERROR
        assert "root" in r.error_message.lower()


class TestResponseCache:
    def test_hit_miss_invalid(self):
        c = ResponseCache(capacity=4)
        r = _req("t")
        assert c.cached(r) == CacheState.MISS
        c.put(msg.Response(types.ALLREDUCE, ["t"]), r)
        assert c.cached(r) == CacheState.HIT
        # same name, different shape -> INVALID (reference:
        # response_cache.cc:50-76)
        assert c.cached(_req("t", shape=(9,))) == CacheState.INVALID

    def test_lru_eviction(self):
        c = ResponseCache(capacity=2)
        c.put(msg.Response(types.ALLREDUCE, ["a"]), _req("a"))
        c.put(msg.Response(types.ALLREDUCE, ["b"]), _req("b"))
        # synchronized touch (fast-path serve) refreshes LRU order
        c.get_by_bit(c.bit_for_name("a"))
        c.put(msg.Response(types.ALLREDUCE, ["c"]), _req("c"))  # evicts b
        assert c.cached(_req("b")) == CacheState.MISS
        assert c.cached(_req("a")) == CacheState.HIT

    def test_local_lookup_does_not_diverge_eviction(self):
        """Workers announce in different orders; cached() must not reorder
        LRU or capacity eviction would pick different victims per worker and
        remap the same cache bit to different tensors (cross-worker
        corruption). Only synchronized paths may touch order."""
        def run(lookup_order):
            c = ResponseCache(capacity=2)
            c.put(msg.Response(types.ALLREDUCE, ["a"]), _req("a"))
            c.put(msg.Response(types.ALLREDUCE, ["b"]), _req("b"))
            for name in lookup_order:  # local announcements, any order
                c.cached(_req(name))
            c.put(msg.Response(types.ALLREDUCE, ["c"]), _req("c"))
            return {n: c.bit_for_name(n)
                    for n in "abc"
                    if c.cached(_req(n)) == CacheState.HIT}

        assert run(["a", "b", "a"]) == run(["b", "a", "b"])

    def test_bits_recycled_after_invalidation(self):
        # a shape-varying tensor renegotiated every step must not grow the
        # bitvector without bound
        c = ResponseCache(capacity=8)
        for step in range(100):
            r = _req("varying", shape=(step + 1,))
            if c.cached(r) == CacheState.INVALID:
                c.invalidate("varying")
            bit = c.put(msg.Response(types.ALLREDUCE, ["varying"]), r)
            assert bit < 8

    def test_bits_recycled_after_eviction(self):
        c = ResponseCache(capacity=2)
        for i in range(50):
            bit = c.put(msg.Response(types.ALLREDUCE, [f"t{i}"]), _req(f"t{i}"))
            assert bit < 3

    def test_coordinator_bitvector(self):
        co = CacheCoordinator()
        co.record_hit(0)
        co.record_hit(5)
        co.set_uncached_in_queue()
        bits = co.bitvector
        assert CacheCoordinator.common_hits(bits) == [0, 5]
        sd, unc, inv = CacheCoordinator.flags(bits)
        assert unc and not sd and not inv


class TestFusion:
    def test_fuse_under_threshold(self):
        reqs = {f"t{i}": _req(f"t{i}", shape=(10,)) for i in range(4)}
        resps = [msg.Response(types.ALLREDUCE, [n]) for n in reqs]
        fused = fusion.fuse_responses(resps, reqs, threshold_bytes=1 << 20)
        assert len(fused) == 1
        assert fused[0].tensor_names == ["t0", "t1", "t2", "t3"]

    def test_threshold_respected(self):
        # each tensor is 400 bytes; threshold 800 -> two per bin
        reqs = {f"t{i}": _req(f"t{i}", shape=(100,)) for i in range(4)}
        resps = [msg.Response(types.ALLREDUCE, [n]) for n in reqs]
        fused = fusion.fuse_responses(resps, reqs, threshold_bytes=800)
        assert [len(f.tensor_names) for f in fused] == [2, 2]

    def test_lookahead_past_dtype_mismatch(self):
        # bf16, fp32, bf16: the two bf16 fuse despite the fp32 between
        # (reference: controller.cc:595-650 look-ahead)
        reqs = {
            "a": _req("a", dtype="bfloat16", shape=(10,)),
            "b": _req("b", dtype="float32", shape=(10,)),
            "c": _req("c", dtype="bfloat16", shape=(10,)),
        }
        resps = [msg.Response(types.ALLREDUCE, [n]) for n in ("a", "b", "c")]
        fused = fusion.fuse_responses(resps, reqs, threshold_bytes=1 << 20)
        assert [f.tensor_names for f in fused] == [["a", "c"], ["b"]]

    def test_byte_accounting_uses_announced_shape(self):
        # announced shapes are per-worker payloads; 100 floats = 400 bytes
        reqs = {"a": _req("a", shape=(100,))}
        r = msg.Response(types.ALLREDUCE, ["a"])
        assert fusion.response_bytes(r, reqs) == 400

    def test_mixed_types_not_fused(self):
        reqs = {
            "a": _req("a"),
            "g": _req("g", rtype=types.ALLGATHER, shape=(3, 2)),
        }
        resps = [msg.Response(types.ALLREDUCE, ["a"]),
                 msg.Response(types.ALLGATHER, ["g"], tensor_sizes=[3])]
        fused = fusion.fuse_responses(resps, reqs, threshold_bytes=1 << 20)
        assert len(fused) == 2


class TestRuntimeEndToEnd:
    """Named async ops through the background cycle loop."""

    def test_named_allreduce(self, hvd):
        vals = [np.full((4,), i, "float32") for i in range(hvd.size())]
        h = hvd.allreduce_async(hvd.stack_per_worker(vals), name="grad/w")
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out),
                                   np.mean(np.stack(vals), 0))

    def test_many_small_tensors_fused_one_cycle(self, hvd, monkeypatch):
        """reference: test_tensorflow.py:152 — many small tensors enqueued
        within one cycle execute correctly and fuse into one program."""
        from horovod_tpu.core import state
        from horovod_tpu.runtime import fusion as fusion_mod
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        fused_tensors_before = fusion_mod._FUSED_TENSORS.value
        # hold the cycle loop (no-op cycles) until all tensors are queued,
        # so they all land in one negotiation cycle
        real_cycle = rt.run_cycle
        monkeypatch.setattr(rt, "run_cycle", lambda: True)
        handles = {}
        for k in range(20):
            vals = [np.full((3,), float(i + k), "float32")
                    for i in range(hvd.size())]
            handles[k] = hvd.allreduce_async(
                hvd.stack_per_worker(vals), name=f"fused/t{k}")
        monkeypatch.setattr(rt, "run_cycle", real_cycle)
        rt._woken.set()
        for k, h in handles.items():
            expected = np.mean([i + k for i in range(hvd.size())])
            np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                       np.full((3,), expected), rtol=1e-6)
        # all 20 went through the fused allreduce path: a bucket-keyed
        # fused program exists and the fusion metrics counted the batch
        # (program keys carry the size bucket, not the member shapes)
        fused_keys = [k for k in rt.executor._programs
                      if k[0] == "fused_allreduce"]
        assert fused_keys, "expected a fused allreduce program"
        assert fusion_mod._FUSED_TENSORS.value - fused_tensors_before >= 20

    def test_steady_state_uses_cache(self, hvd):
        from horovod_tpu.core import state

        for step in range(3):
            hs = [hvd.allreduce_async(
                hvd.stack_per_worker(
                    [np.full((2,), float(i), "float32")
                     for i in range(hvd.size())]),
                name=f"cache/t{j}") for j in range(4)]
            for h in hs:
                hvd.synchronize(h)
        cache = state.global_state().runtime.controller.cache
        assert len(cache) == 4

    def test_named_allgather(self, hvd):
        vals = [np.full((2, 3), i, "float32") for i in range(hvd.size())]
        h = hvd.allgather_async(hvd.stack_per_worker(vals), name="ag/x")
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.concatenate(vals, 0))

    def test_named_broadcast(self, hvd):
        vals = [np.full((4,), i, "float32") for i in range(hvd.size())]
        h = hvd.broadcast_async(hvd.stack_per_worker(vals), root_rank=5,
                                name="bc/x")
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)), vals[5])

    def test_duplicate_inflight_name_raises(self, hvd):
        from horovod_tpu.core import state
        rt_mod = __import__("horovod_tpu.runtime.runtime",
                            fromlist=["get_runtime"])
        rt = rt_mod.get_runtime()
        # pause the cycle loop by stopping pops: enqueue twice quickly
        x = hvd.stack_per_worker(
            [np.ones((2,), "float32")] * hvd.size())
        # enqueue directly to guarantee both before a cycle runs
        rt.queue.add(
            types.TensorTableEntry(name="dup/x", tensor=x),
            _req("dup/x"))
        with pytest.raises(DuplicateNameError):
            rt.queue.add(
                types.TensorTableEntry(name="dup/x", tensor=x),
                _req("dup/x"))
        # drain
        rt.queue.get_entries(["dup/x"])

    def test_fp16_compressed_named_allreduce(self, hvd):
        vals = [np.full((8,), i / 7.0, "float32") for i in range(hvd.size())]
        h = hvd.allreduce_async(hvd.stack_per_worker(vals), name="comp/x",
                                compression=hvd.Compression.fp16)
        out = hvd.synchronize(h)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out),
                                   np.mean(np.stack(vals), 0), rtol=1e-2)

    def test_shutdown_flushes_pending(self, hvd):
        import horovod_tpu as hvd_mod
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        rt.stop()
        with pytest.raises(RuntimeError, match="shut down"):
            rt.enqueue_allreduce(
                "late/x",
                hvd.stack_per_worker([np.ones(2, "float32")] * hvd.size()))


class TestStallInspector:
    def test_warning_and_shutdown(self, caplog):
        from horovod_tpu.runtime.controller import MessageTable
        from horovod_tpu.stall import StallInspector

        table = MessageTable()
        table.increment(_req("stuck", rank=0), world=2)  # 1 of 2 ranks
        insp = StallInspector(warning_time_seconds=0.0,
                              shutdown_time_seconds=0.05)
        assert insp.check(table, world=2) is False  # first sighting
        time.sleep(0.06)
        assert insp.check(table, world=2) is True  # exceeded shutdown

    def test_disabled(self):
        from horovod_tpu.runtime.controller import MessageTable
        from horovod_tpu.stall import StallInspector

        insp = StallInspector(enabled=False, warning_time_seconds=0.0)
        assert insp.check(MessageTable()) is False


class TestReduceScatterAlltoallNegotiation:
    def test_message_roundtrip_new_types(self):
        for rtype in (types.REDUCESCATTER, types.ALLTOALL):
            r = _req("t", rtype=rtype, shape=(8, 3), reduce_op="min")
            out, _ = msg.Request.unpack(r.pack())
            assert out == r
        resp = msg.Response(types.REDUCESCATTER, ["t"])
        assert msg.Response.unpack(resp.pack())[0].response_type == \
            types.REDUCESCATTER

    def test_construct_response_validates(self):
        ok = construct_response([
            _req("t", rank=0, rtype=types.REDUCESCATTER, shape=(4, 3)),
            _req("t", rank=1, rtype=types.REDUCESCATTER, shape=(4, 3))])
        assert ok.response_type == types.REDUCESCATTER
        bad_shape = construct_response([
            _req("t", rank=0, rtype=types.REDUCESCATTER, shape=(4, 3)),
            _req("t", rank=1, rtype=types.REDUCESCATTER, shape=(6, 3))])
        assert bad_shape.response_type == types.ERROR
        bad_op = construct_response([
            _req("t", rank=0, rtype=types.REDUCESCATTER, shape=(4, 3),
                 reduce_op="sum"),
            _req("t", rank=1, rtype=types.REDUCESCATTER, shape=(4, 3),
                 reduce_op="min")])
        assert "reduction ops" in bad_op.error_message
        indivisible = construct_response([
            _req("t", rank=0, rtype=types.REDUCESCATTER, shape=(3, 3)),
            _req("t", rank=1, rtype=types.REDUCESCATTER, shape=(3, 3))])
        assert "divide evenly" in indivisible.error_message
        a2a_bad = construct_response([
            _req("t", rank=0, rtype=types.ALLTOALL, shape=(4, 3)),
            _req("t", rank=1, rtype=types.ALLTOALL, shape=(4, 2))])
        assert a2a_bad.response_type == types.ERROR
        a2a_ok = construct_response([
            _req("t", rank=0, rtype=types.ALLTOALL, shape=(4, 3)),
            _req("t", rank=1, rtype=types.ALLTOALL, shape=(4, 3))])
        assert a2a_ok.response_type == types.ALLTOALL


class TestEntryCompletion:
    def test_complete_fires_exactly_once(self):
        calls = []
        e = types.TensorTableEntry(
            name="x", tensor=None,
            callback=lambda s, o: calls.append((s, o)))
        e.complete(types.Status.OK(), 1)
        e.complete(types.Status.Aborted("late"), None)
        assert calls == [(calls[0][0], 1)] and calls[0][0].ok()

    def test_fail_incomplete_guards_any_callable(self):
        """The double-complete guard must hold for plain function
        callbacks (e.g. framework-binding wrappers), not only bound
        methods of a pollable handle."""
        from horovod_tpu.runtime.runtime import _fail_incomplete_entries

        calls = []
        done = types.TensorTableEntry(
            name="x", tensor=None, callback=lambda s, o: calls.append(s))
        done.complete(types.Status.OK(), None)
        pending = types.TensorTableEntry(
            name="y", tensor=None, callback=lambda s, o: calls.append(s))
        _fail_incomplete_entries([done, pending])
        assert len(calls) == 2  # done NOT re-fired; pending failed once
        assert calls[0].ok() and not calls[1].ok()


class TestCycleFailureHandling:
    def test_cycle_exception_fails_popped_entries(self, hvd_flat):
        """An exception mid-cycle must complete the claimed handles with
        an error, not strand them (reference: any rank failure surfaces,
        never hangs)."""
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        original = rt.executor.dispatch  # the cycle body dispatches
        try:
            def boom(*a, **k):
                raise RuntimeError("injected executor failure")

            rt.executor.dispatch = boom
            h = rt.enqueue_allreduce("cycfail/x",
                                     jnp.ones((4,), jnp.float32))
            with pytest.raises(RuntimeError):
                h.wait()
            # the name is free again (not poisoned by a stranded entry)
            rt.executor.dispatch = original
            h2 = rt.enqueue_allreduce("cycfail/x",
                                      jnp.ones((4,), jnp.float32))
            out = h2.wait()
            np.testing.assert_allclose(np.asarray(out), 1.0)
        finally:
            rt.executor.dispatch = original

    def test_enqueue_after_loop_exit_raises(self, hvd_flat):
        """Once the background loop exits (any path), new enqueues raise
        SHUT_DOWN_ERROR instead of queueing into a dead loop."""
        from horovod_tpu.runtime.runtime import get_runtime

        rt = get_runtime()
        rt.stop()
        with pytest.raises(RuntimeError):
            rt.enqueue_allreduce("dead/x", jnp.ones((2,), jnp.float32))
