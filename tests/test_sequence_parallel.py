"""Ring attention + Ulysses sequence parallelism vs single-device ground
truth, on the virtual 8-device CPU mesh (SURVEY.md §4 strategy)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.pallas import (
    attention_reference,
    flash_attention,
    flash_attention_partial,
    merge_partials,
)
from horovod_tpu.parallel.ring import ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention

B, H, S, D = 2, 8, 256, 32
N_DEV = 8


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)), dtype)
    return mk(), mk(), mk()


def _seq_mesh():
    return Mesh(np.array(jax.devices()).reshape(N_DEV), ("sp",))


# ---------------------------------------------------------------------------
# single-device kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(o, ref, atol=2e-5)


@functools.lru_cache(maxsize=2)
def _dispatch_ref_grads(causal):
    """Reference gradients for test_flash_dispatch_matrix — identical
    across the four block parametrizations, so computed once per
    causal flag."""
    q, k, v = _qkv(7)

    def loss(q, k, v):
        return jnp.mean(attention_reference(
            q, k, v, causal=causal).astype(jnp.float32) ** 2)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "blocks",
    [
        # (block_q, block_k, bwd_block_q, bwd_block_k) spanning the r5
        # dispatch matrix at S=256:
        (512, 1024, 1024, 1024),  # single fwd + dq/dkv single (defaults)
        (128, 1024, 128, 1024),   # single fwd multi-q (wedge), dkv general
        (512, 1024, 1024, 128),   # dq general, dkv single multi-k
        (64, 64, 64, 64),         # fully general (online softmax)
    ],
    ids=["all-single", "dq-single-wedge", "dkv-single", "all-general"])
def test_flash_dispatch_matrix(causal, blocks):
    """The r5 single-block specialization added four dispatch paths
    (single-k-block direct-softmax fwd with causal wedge; scratch-free
    dq and dk/dv single kernels composing with the general pair). Every
    combination must match the reference in both output and gradients
    — this pins the path selection itself, not just the default."""
    bq, bk, bbq, bbk = blocks
    q, k, v = _qkv(7)

    kw = dict(causal=causal, block_q=bq, block_k=bk,
              bwd_block_q=bbq, bwd_block_k=bbk)
    o = flash_attention(q, k, v, **kw)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(o, ref, atol=2e-5)

    def loss(fn):
        # squared output -> the cotangent do = 2*o/n VARIES per row and
        # block, so a backward BlockSpec indexing the wrong do block
        # cannot cancel out (a constant cotangent would hide it)
        return lambda q, k, v: jnp.mean(
            fn(q, k, v).astype(jnp.float32) ** 2)

    g_ref = _dispatch_ref_grads(causal)
    g_fl = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, **kw)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = _qkv(1)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(attention_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(a, b, atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_mxu_path(causal, monkeypatch):
    """FLASH_MXU_BF16=1 feeds the MXU dots bf16 operands (f32
    accumulation). Outputs and grads must match the f32 reference
    computed on the same (bf16-rounded) inputs to bf16-appropriate
    tolerance; the default (flag off) keeps the f32-cast path."""
    q, k, v = _qkv(7, dtype=jnp.bfloat16)
    ref = attention_reference(q, k, v, causal=causal).astype(jnp.float32)

    # default: f32-cast path
    o_f32 = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(o_f32, np.float32), ref,
                               atol=2e-2)

    monkeypatch.setenv("FLASH_MXU_BF16", "1")
    o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(o, np.float32), ref, atol=2e-2)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(attention_reference), argnums=(0, 1, 2))(q, k, v)
    scale = max(float(jnp.abs(x).max()) for x in gr)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=3e-2 * scale)


def test_flash_cross_offsets():
    """Offsets shift the causal mask to global positions."""
    q, k, v = _qkv(2)
    # queries are the second half of a virtual 2S sequence; keys the first.
    o = flash_attention(q, k, v, causal=True, q_offset=S, k_offset=0)
    # every key is in the past -> equivalent to non-causal
    ref = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(o, ref, atol=2e-5)
    # queries strictly before all keys -> fully masked -> zeros
    o2, lse2 = flash_attention_partial(q, k, v, causal=True,
                                       q_offset=0, k_offset=S)
    assert float(jnp.abs(o2).max()) == 0.0
    assert bool(jnp.all(lse2 == float("-inf")))


def test_flash_partially_masked_block():
    """Regression: rows fully masked within a *processed* k block must give
    exactly zero output and -inf lse (k_offset inside the q range, so the
    kernel cannot skip the block)."""
    q, k, v = _qkv(9)
    o, lse = flash_attention_partial(q, k, v, causal=True,
                                     q_offset=0, k_offset=S // 2)
    # rows < S//2 see no keys at all
    np.testing.assert_array_equal(np.asarray(o[:, :, : S // 2]), 0.0)
    assert bool(jnp.all(lse[:, :, : S // 2] == float("-inf")))
    # remaining rows must match the reference on the shifted window
    ref = attention_reference(q, k, v, causal=True, q_offset=0,
                              k_offset=S // 2)
    np.testing.assert_allclose(np.asarray(o[:, :, S // 2:]),
                               ref[:, :, S // 2:], atol=2e-5)
    # and merging with a genuinely-absent partial must not revive them
    om, _ = merge_partials(o, lse, jnp.zeros_like(o),
                           jnp.full(lse.shape, float("-inf")))
    np.testing.assert_array_equal(np.asarray(om[:, :, : S // 2]), 0.0)


def test_merge_partials_associative():
    q, k, v = _qkv(3)
    third = S // 4
    parts = []
    for i in range(4):
        sl = slice(i * third, (i + 1) * third)
        parts.append(flash_attention_partial(
            q, k[:, :, sl], v[:, :, sl], causal=True,
            q_offset=0, k_offset=i * third))
    o, lse = parts[0]
    for o_p, lse_p in parts[1:]:
        o, lse = merge_partials(o, lse, o_p, lse_p)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(o, ref, atol=2e-5)


# ---------------------------------------------------------------------------
# ring attention under shard_map
# ---------------------------------------------------------------------------


# the non-causal ring schedule lowers to a PartitionId instruction that
# XLA's CPU SPMD partitioner rejects ("PartitionId instruction is not
# supported for SPMD partitioning"); TPU/GPU partitioners implement it
_causal_modes = [
    pytest.param(False, marks=pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="XLA CPU SPMD partitioner does not support PartitionId")),
    True,
]


@pytest.mark.parametrize("causal", _causal_modes)
def test_ring_attention_matches_full(causal):
    q, k, v = _qkv(4)
    mesh = _seq_mesh()

    def local(q, k, v):
        return ring_attention(q, k, v, "sp", causal, None, 32, 32)

    f = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"), check_vma=False))
    o = f(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), ref, atol=2e-5)


@pytest.mark.parametrize("causal", _causal_modes)
def test_ring_attention_grads(causal):
    q, k, v = _qkv(5)
    mesh = _seq_mesh()

    def local(q, k, v):
        return ring_attention(q, k, v, "sp", causal, None, 32, 32)

    sharded = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"), check_vma=False)

    def loss(q, k, v):
        return jnp.sum(sharded(q, k, v).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=causal).astype(jnp.float32)
            ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-4)


# ---------------------------------------------------------------------------
# Ulysses under shard_map
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    q, k, v = _qkv(6)
    mesh = _seq_mesh()

    def local(q, k, v):
        return ulysses_attention(q, k, v, "sp", causal=causal,
                                 block_q=32, block_k=32)

    f = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"), check_vma=False))
    o = f(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), ref, atol=2e-5)


def test_ulysses_grads():
    q, k, v = _qkv(7)
    mesh = _seq_mesh()

    sharded = jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True,
                                          block_q=32, block_k=32),
        mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"), check_vma=False)

    def loss(q, k, v):
        return jnp.sum(sharded(q, k, v).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            attention_reference(q, k, v, causal=True).astype(jnp.float32)
            ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-4)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(8)
    q3 = q[:, :3]
    mesh = _seq_mesh()
    with pytest.raises(ValueError, match="divisible"):
        jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "sp"),
            mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"), check_vma=False)(q3, k[:, :3], v[:, :3])
