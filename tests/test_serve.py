"""Unit coverage for the serving plane (serve/; docs/inference.md).

Four pinned-down contracts:

* the continuous batcher's admission policy matrix — token budget as a
  hard cap, slots, deadline-beats-decode-block — on a fake clock (the
  batcher never touches jax, so this is pure scheduling);
* the shared request queue's zero-lost invariant: worker loss returns
  in-flight requests to the FRONT of the line, oldest first, and the
  first completion writer wins;
* the KV-cache engine: prefill + per-token decode must be
  token-for-token identical to greedy generation through the uncached
  ``apply`` (padded prefill garbage and stale slot-reuse rows are
  unreachable by construction), with zero steady-state compiles;
* replica integrity: a NaN logit quarantines the replica and requeues
  its work; ``WorkersDownError`` requeues and re-raises.

The multiprocess half (kill-a-replica-under-load) lives in
tests/test_serve_multiprocess.py.
"""

import math
import threading
import time

import pytest

from horovod_tpu.exceptions import WorkersDownError
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.queue import (KVQueueFrontend, KVQueueReplica,
                                     QueueFull, Completion, Request,
                                     RequestQueue)


def _req(uid, prompt_len=8, max_new=4):
    return Request(uid=uid, prompt=list(range(1, prompt_len + 1)),
                   max_new_tokens=max_new, submitted_s=0.0)


# ---------------------------------------------------------------- batcher

class TestBatcherPolicy:
    def _batcher(self, slots=4, budget=10_000, admission_ms=50.0,
                 block=8):
        return ContinuousBatcher(num_slots=slots, max_batch_tokens=budget,
                                 admission_ms=admission_ms,
                                 decode_block=block)

    def test_idle_replica_admits_immediately(self):
        b = self._batcher()
        assert not b.admission_due(0.0)          # nothing waiting
        b.offer(_req("a"), now=0.0)
        assert b.admission_due(0.0)              # idle: no block to honor
        assert [a.request.uid for a in b.admit(0.0)] == ["a"]
        assert b.occupancy() == 1 and b.waiting() == 0

    def test_token_budget_is_a_hard_cap(self):
        # each request commits prompt(8) + max_new(4) = 12 tokens
        b = self._batcher(budget=25, admission_ms=50.0)
        for uid in ("a", "b", "c"):
            b.offer(_req(uid), now=0.0)
        admitted = b.admit(0.0)
        assert [a.request.uid for a in admitted] == ["a", "b"]
        assert b.committed_tokens() == 24
        # the deadline fires but must NOT override the budget
        assert b.admission_due(9.0)
        assert b.admit(9.0) == []
        # a retired request frees budget; the head then admits
        b.active()[0].generated.extend([1, 2, 3, 4])
        assert [a.request.uid for a in b.retire_done()] == ["a"]
        assert [a.request.uid for a in b.admit(9.0)] == ["c"]

    def test_budget_blocked_head_blocks_younger(self):
        # FIFO no-starvation: the big head does not let the small
        # request behind it jump the line
        b = self._batcher(budget=20)
        b.offer(_req("big", prompt_len=30, max_new=4), now=0.0)
        b.offer(_req("small", prompt_len=2, max_new=4), now=0.0)
        assert b.admit(0.0) == []
        assert b.waiting() == 2

    def test_deadline_beats_decode_block(self):
        b = self._batcher(admission_ms=50.0, block=1000)
        b.offer(_req("a"), now=0.0)
        b.admit(0.0)
        b.offer(_req("b"), now=1.0)
        assert not b.admission_due(1.04)     # young + mid-block
        assert b.admission_due(1.051)        # deadline pulls it forward

    def test_decode_block_boundary(self):
        b = self._batcher(slots=1, admission_ms=1e9, block=3)
        b.offer(_req("a", max_new=100), now=0.0)
        b.admit(0.0)
        b.offer(_req("b"), now=0.0)
        for _ in range(2):
            assert not b.admission_due(0.0)
            b.note_step()
        b.note_step()
        assert b.admission_due(0.0)
        b.admit(0.0)                         # slot full: admits nothing,
        assert b.occupancy() == 1            # but resets the block count
        assert not b.admission_due(0.0)

    def test_admission_caps_generation_to_cache(self):
        # prompt(12) + max_new(10) overruns max_seq=16: the effective
        # generation length is capped at admission (the last token is
        # returned, never written, hence the +1) — never silently
        # clamped onto the last KV row mid-decode
        b = ContinuousBatcher(num_slots=2, max_batch_tokens=10_000,
                              admission_ms=50.0, decode_block=8,
                              max_seq=16)
        b.offer(_req("a", prompt_len=12, max_new=10), now=0.0)
        b.offer(_req("b", prompt_len=4, max_new=10), now=0.0)
        capped, fits = b.admit(0.0)
        assert capped.max_tokens == 5 and capped.capped
        assert fits.max_tokens == 10 and not fits.capped
        # the budget charges the EFFECTIVE commitment, not the asked-for
        assert b.committed_tokens() == (12 + 5) + (4 + 10)
        capped.generated.extend([1] * 5)
        assert capped.done                   # done at the cap

    def test_slots_cap(self):
        b = self._batcher(slots=2)
        for uid in ("a", "b", "c"):
            b.offer(_req(uid), now=0.0)
        assert len(b.admit(0.0)) == 2
        assert b.waiting() == 1

    def test_batch_rows_retire_evict_drain(self):
        b = self._batcher(slots=2)
        b.offer(_req("a", prompt_len=3, max_new=2), now=0.0)
        b.offer(_req("b", prompt_len=5, max_new=9), now=0.0)
        b.offer(_req("c"), now=0.0)
        b.admit(0.0)
        # right after prefill-less admit: last prompt token, position
        # = prompt_len (where the next token writes)
        slots, tokens, positions = b.batch_rows()
        assert slots == [0, 1] and tokens == [3, 5] and positions == [3, 5]
        a = b.active()[0]
        a.generated.extend([7, 8])
        a.position += 2
        assert [d.request.uid for d in b.retire_done()] == ["a"]
        slots, tokens, positions = b.batch_rows()
        assert slots == [1] and tokens == [5]
        assert [r.uid for r in b.evict_all()] == ["b"]
        assert [r.uid for r in b.drain_waiting()] == ["c"]
        assert b.occupancy() == 0 and b.waiting() == 0
        assert len(b.admit(0.0)) == 0        # everything really drained


# ------------------------------------------------------------------ queue

class TestRequestQueue:
    def test_submit_pull_complete_result(self):
        q = RequestQueue()
        uid = q.submit([1, 2, 3], max_new_tokens=4)
        assert q.try_result(uid) is None
        (req,) = q.pull(rank=0, max_n=8)
        assert req.uid == uid and q.depth() == 0
        q.complete(Completion(uid=uid, tokens=[9], prompt_len=3, rank=0))
        assert q.result(uid, timeout=1.0).tokens == [9]
        assert q.stats()["inflight"] == 0

    def test_requeue_worker_front_oldest_first(self):
        q = RequestQueue()
        uids = [q.submit([i], max_new_tokens=1) for i in range(3)]
        later = q.submit([9], max_new_tokens=1)
        pulled = q.pull(rank=0, max_n=3)
        assert [r.uid for r in pulled] == uids
        assert q.requeue_worker(0) == 3
        # stranded requests go back to the FRONT, oldest first — ahead
        # of the younger request that was never pulled
        assert [r.uid for r in q.pull(rank=1, max_n=10)] == uids + [later]
        assert q.requeue_worker(0) == 0
        assert q.stats()["requeued"] == 3

    def test_first_completion_wins(self):
        q = RequestQueue()
        uid = q.submit([1], max_new_tokens=1)
        q.pull(rank=0, max_n=1)
        q.complete(Completion(uid=uid, tokens=[1], prompt_len=1, rank=0))
        q.complete(Completion(uid=uid, tokens=[2], prompt_len=1, rank=1))
        assert q.result(uid).rank == 0       # duplicate reply discarded

    def test_results_evicted_after_ttl(self):
        # a serving process must not hold one Completion per request
        # ever served; eviction is amortized on the complete() path
        q = RequestQueue(result_ttl=0.05)
        uid = q.submit([1], max_new_tokens=1)
        q.pull(rank=0, max_n=1)
        q.complete(Completion(uid=uid, tokens=[1], prompt_len=1, rank=0))
        assert q.result(uid, timeout=1.0).tokens == [1]
        time.sleep(0.06)
        uid2 = q.submit([2], max_new_tokens=1)
        q.pull(rank=0, max_n=1)
        q.complete(Completion(uid=uid2, tokens=[2], prompt_len=1, rank=0))
        assert q.try_result(uid) is None          # evicted
        assert q.try_result(uid2) is not None     # fresh result kept
        stats = q.stats()
        assert stats["completed"] == 2            # counter, not dict size
        assert stats["results_held"] == 1

    def test_capacity_and_timeout(self):
        q = RequestQueue(capacity=1)
        q.submit([1], max_new_tokens=1)
        with pytest.raises(QueueFull):
            q.submit([2], max_new_tokens=1)
        with pytest.raises(TimeoutError):
            q.result("nope", timeout=0.05)


# ---------------------------------------------------------- prompt buckets

def test_prompt_bucket_policy():
    from horovod_tpu.serve.kv_cache import prompt_bucket

    # floored at the quantum: every short prompt shares ONE program
    assert prompt_bucket(1, 128) == 16
    assert prompt_bucket(16, 128) == 16
    assert prompt_bucket(17, 128) > 16
    for length in range(1, 129):
        b = prompt_bucket(length, 128)
        assert length <= b <= 128 or b == 128
    # O(log(max_seq)) distinct buckets → bounded warmup compiles
    assert len({prompt_bucket(n, 1024) for n in range(1, 1025)}) <= 8


# ------------------------------------------------------------------ engine

@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import Transformer

    model = Transformer(vocab_size=61, d_model=32, num_layers=2,
                        num_heads=2, d_ff=64, max_seq=48, causal=True,
                        dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return model, params


def _uncached_greedy(model, params, prompt, n):
    """Reference: full (cache-free) forward per token, greedy argmax."""
    import jax.numpy as jnp

    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([toks], jnp.int32), train=False)
        out.append(int(jnp.argmax(logits[0, len(toks) - 1])))
        toks.append(out[-1])
    return out


def test_prefill_decode_parity_and_isolation(tiny_lm):
    """Two concurrent slots (different prompt buckets) each generate
    token-for-token what the uncached apply generates — proving the
    cache path, the padded prefill, AND cross-slot isolation at once."""
    from horovod_tpu.serve.kv_cache import DecodeEngine

    model, params = tiny_lm
    eng = DecodeEngine(model, params, num_slots=3)
    prompts = {0: [5, 4, 3, 2, 1], 2: list(range(1, 18))}
    gen, pos = {}, {}
    for slot, p in prompts.items():
        token, max_abs = eng.prefill(slot, p)
        assert math.isfinite(max_abs)
        gen[slot] = [token]
        pos[slot] = len(p)
    for _ in range(5):
        slots = sorted(prompts)
        ids, max_abs = eng.decode(slots, [gen[s][-1] for s in slots],
                                  [pos[s] for s in slots])
        assert all(math.isfinite(m) for m in max_abs)
        for s, t in zip(slots, ids):
            gen[s].append(t)
            pos[s] += 1
    for slot, p in prompts.items():
        assert gen[slot] == _uncached_greedy(model, params, p, 6), slot


def test_slot_reuse_no_stale_leak(tiny_lm):
    """A short prompt re-using the slot a LONGER request just vacated
    must generate exactly what it generates in a fresh engine — the
    previous occupant's stale rows beyond the new prompt are never
    attendable."""
    from horovod_tpu.serve.kv_cache import DecodeEngine

    model, params = tiny_lm

    def run(eng, slot, prompt, n):
        token, _ = eng.prefill(slot, prompt)
        out, p = [token], len(prompt)
        for _ in range(n - 1):
            (t,), _ = eng.decode([slot], [out[-1]], [p])
            out.append(t)
            p += 1
        return out

    used = DecodeEngine(model, params, num_slots=2)
    run(used, 1, list(range(1, 31)), 8)      # long occupant fills rows
    fresh = DecodeEngine(model, params, num_slots=2)
    short = [9, 8, 7, 6]
    assert run(used, 1, short, 6) == run(fresh, 1, short, 6)


def test_zero_steady_state_compiles(tiny_lm):
    from horovod_tpu.serve.kv_cache import DecodeEngine

    model, params = tiny_lm
    eng = DecodeEngine(model, params, num_slots=2)
    eng.prefill(0, [1, 2, 3])
    eng.decode([0], [1], [3])
    eng.prefill(1, [4, 5])                   # same bucket: no new program
    warm = eng.compiles_total()
    assert warm == 2                          # one prefill bucket + decode
    for step in range(5):
        eng.prefill(step % 2, [7, 8, 9])
        eng.decode([0, 1], [1, 2], [4, 5])
    assert eng.compiles_total() == warm
    assert eng.prefill(0, list(range(1, 20)))  # new bucket DOES compile
    assert eng.compiles_total() == warm + 1
    assert eng.stats()["decode_steps"] == 6


def test_noncausal_model_rejected(tiny_lm):
    from horovod_tpu.serve.kv_cache import DecodeEngine

    model, params = tiny_lm
    with pytest.raises(ValueError, match="causal"):
        DecodeEngine(model.clone(causal=False), params, num_slots=1)


# ----------------------------------------------------- replica integrity

class _FakeEngine:
    """Minimal engine double for the replica loop (no jax)."""

    def __init__(self, num_slots=2, prefill_abs=1.0, decode_abs=1.0,
                 decode_exc=None):
        self.num_slots = num_slots
        self.max_seq = 64
        self.decode_steps = 0
        self._prefill_abs = prefill_abs
        self._decode_abs = decode_abs
        self._decode_exc = decode_exc

    def prefill(self, slot, prompt):
        return 1, self._prefill_abs

    def decode(self, slots, tokens, positions):
        if self._decode_exc is not None:
            raise self._decode_exc
        self.decode_steps += 1
        abs_ = (list(self._decode_abs)
                if isinstance(self._decode_abs, (list, tuple))
                else [self._decode_abs] * len(slots))
        return [2] * len(slots), abs_[:len(slots)]

    def compiles_total(self):
        return 0

    def stats(self):
        return {"decode_steps": self.decode_steps}


def _replica(engine, queue, rank=0):
    from horovod_tpu.serve.api import ServePolicy
    from horovod_tpu.serve.replica import Replica, _LocalTransport

    return Replica(engine, _LocalTransport(queue, rank),
                   ServePolicy(slots=engine.num_slots, max_new_tokens=4,
                               admission_ms=1.0, decode_block=2),
                   rank=rank)


def test_nan_prefill_quarantines_and_requeues():
    q = RequestQueue()
    rep = _replica(_FakeEngine(prefill_abs=float("nan")), q)
    q.submit([1, 2], max_new_tokens=4)
    rep._iterate()
    assert rep.quarantined
    # zero lost: the request is back in line for another replica
    assert q.depth() == 1 and q.stats()["requeued"] == 1

def test_nan_decode_quarantines_and_requeues():
    q = RequestQueue()
    rep = _replica(_FakeEngine(decode_abs=float("inf")), q)
    q.submit([1, 2], max_new_tokens=4)
    rep._iterate()
    assert rep.quarantined
    assert q.depth() == 1 and q.stats()["requeued"] == 1


def test_workers_down_requeues_and_reraises():
    q = RequestQueue()
    rep = _replica(_FakeEngine(decode_exc=WorkersDownError("reform")), q)
    q.submit([1, 2], max_new_tokens=4)
    with pytest.raises(WorkersDownError):
        rep.run()
    assert not rep.quarantined               # elastic, not integrity
    assert q.depth() == 1 and q.stats()["requeued"] == 1


def test_replica_rejects_unservable_prompts():
    """A prompt longer than the cache (or empty) arriving over the
    transport — bypassing ServeHandle's validation — must be answered
    with finish="rejected", not crash the loop or strand its caller."""
    q = RequestQueue()
    rep = _replica(_FakeEngine(), q)             # max_seq = 64
    uid_long = q.submit(list(range(100)), max_new_tokens=4)
    uid_empty = q.submit([], max_new_tokens=4)
    rep._iterate()
    assert q.result(uid_long, timeout=1.0).finish == "rejected"
    assert q.result(uid_empty, timeout=1.0).finish == "rejected"
    assert not rep.quarantined and q.stats()["inflight"] == 0


def test_loop_error_quarantines_and_requeues():
    """A non-elastic exception escaping the step must not silently kill
    the replica thread (stranding in-flight callers): the replica
    requeues its work and parks quarantined."""
    q = RequestQueue()
    rep = _replica(_FakeEngine(decode_exc=RuntimeError("boom")), q)
    q.submit([1, 2], max_new_tokens=4)
    t = threading.Thread(target=rep.run, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while not rep.quarantined and time.monotonic() < deadline:
        time.sleep(0.01)
    rep.stop()
    t.join(timeout=5.0)
    assert rep.quarantined and not t.is_alive()
    assert q.depth() == 1 and q.stats()["requeued"] == 1


def test_guard_observes_every_slot():
    """The integrity guard's EWMA state must see EVERY slot's max-|logit|
    each step — a non-finite first slot must not short-circuit the
    observations of the slots behind it."""
    class _CountingGuard:
        def __init__(self):
            self.seen = []

        def observe(self, value):
            self.seen.append(value)

    q = RequestQueue()
    rep = _replica(_FakeEngine(decode_abs=[float("nan"), 5.0]), q)
    rep.guard = _CountingGuard()
    q.submit([1, 2], max_new_tokens=4)
    q.submit([3, 4], max_new_tokens=4)
    rep._iterate()                               # prefill x2 + decode
    assert rep.quarantined                       # nan still trips it
    assert 5.0 in rep.guard.seen                 # second slot observed


def test_healthy_replica_completes():
    q = RequestQueue()
    rep = _replica(_FakeEngine(), q)
    uid = q.submit([1, 2], max_new_tokens=3)
    for _ in range(4):
        rep._iterate()
    done = q.result(uid, timeout=1.0)
    assert done.tokens == [1, 2, 2] and done.rank == 0
    assert not rep.quarantined and rep.completed == 1


# ----------------------------------------------------------- policy / api

def test_policy_from_env_and_overrides(monkeypatch):
    from horovod_tpu.serve.api import ServePolicy

    monkeypatch.setenv("HOROVOD_SERVE_SLOTS", "3")
    monkeypatch.setenv("HOROVOD_SERVE_ADMISSION_MS", "12.5")
    p = ServePolicy.from_env(max_new_tokens=7)
    assert p.slots == 3 and p.admission_ms == 12.5
    assert p.max_new_tokens == 7
    with pytest.raises(TypeError, match="unknown serve policy knob"):
        ServePolicy.from_env(slotz=3)


class _Tokenizer:
    def encode(self, text):
        return [ord(c) % 50 + 1 for c in text]


def test_submit_validates_prompt_against_max_seq():
    """Oversized / empty prompts are refused AT SUBMIT — the caller gets
    a ValueError now, not a result() timeout after the replica choked;
    a prompt that fits but overruns the cache with its generation budget
    is served truncated with finish="cache_limit"."""
    from horovod_tpu.serve.api import ServeHandle, ServePolicy

    q = RequestQueue()
    rep = _replica(_FakeEngine(), q)             # max_seq = 64
    handle = ServeHandle([rep], q, ServePolicy(max_new_tokens=4))
    try:
        with pytest.raises(ValueError, match="empty"):
            handle.submit([])
        with pytest.raises(ValueError, match="max_seq"):
            handle.submit([1] * 65)
        done = handle.generate([1] * 64, timeout=10.0)  # exactly fits
        assert done.finish == "cache_limit"       # cache, not budget
        assert len(done.tokens) == 1              # prefill token only
        done = handle.generate([1] * 8, timeout=10.0)
        assert done.finish == "length" and len(done.tokens) == 4
    finally:
        handle.close()


def test_serve_end_to_end_in_process(tiny_lm):
    import horovod_tpu as hvd
    from horovod_tpu.serve import serve_state

    model, params = tiny_lm
    with hvd.serve(model, params, tokenizer=_Tokenizer(), replicas=2,
                   slots=2, max_new_tokens=5, admission_ms=5.0,
                   decode_block=2) as handle:
        assert serve_state()["count"] == 1   # the /serve route sees us
        uids = [handle.submit([1 + i, 2, 3]) for i in range(6)]
        uids.append(handle.submit("hi"))     # tokenizer path
        outs = [handle.result(u, timeout=120.0) for u in uids]
        assert all(len(o.tokens) == 5 for o in outs)
        assert all(0 <= t < model.vocab_size
                   for o in outs for t in o.tokens)
        assert all(o.latency_s >= o.ttft_s >= 0.0 for o in outs)
        # parity with the uncached reference through the full stack
        assert outs[0].tokens == _uncached_greedy(
            model, params, [1, 2, 3], 5)
        # per replica: one prompt bucket + the decode program, at most
        assert handle.compiles_total() <= 4
        stats = handle.stats()
        assert stats["queue"]["completed"] == 7
    assert serve_state()["count"] == 0


# --------------------------------------------------------- KV transport

def test_kv_frontend_redispatches_dead_replica():
    """Single-process version of the chaos cell's queue semantics: a
    replica that pulled work and went silent is declared dead after the
    stale window and its request re-dispatched to a live replica; the
    late duplicate reply (if any) is deduplicated first-wins."""
    from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer

    server = RendezvousServer(host="127.0.0.1")
    port = server.start()
    try:
        def client():
            return KVStoreClient("127.0.0.1", port, scope="serve",
                                 timeout=5.0)

        front = KVQueueFrontend(client(), stale_seconds=0.4)
        dead = KVQueueReplica(client(), rank=1)
        live = KVQueueReplica(client(), rank=2)
        dead.heartbeat()
        live.heartbeat()
        assert front.wait_for_replicas(2, timeout=5.0) == [1, 2]

        req = Request(uid="r1", prompt=[1, 2, 3], max_new_tokens=2)
        assert front.submit(req, rank=1) == 1
        (got,) = dead.poll(4)
        assert got.uid == "r1"               # pulled... then rank 1 dies
        deadline = time.monotonic() + 5.0
        while 1 in front.live_replicas() and time.monotonic() < deadline:
            live.heartbeat()
            time.sleep(0.05)
        assert front.live_replicas() == [2]
        assert front.poll_responses() == []  # triggers the re-dispatch
        assert front.requeued == 1 and front.dead_ranks == {1}
        (redis,) = live.poll(4)
        assert redis.uid == "r1"
        live.complete(Completion(uid="r1", tokens=[5, 6], prompt_len=3,
                                 rank=2))
        deadline = time.monotonic() + 5.0
        while front.pending() and time.monotonic() < deadline:
            front.poll_responses()
            time.sleep(0.02)
        assert front.pending() == 0
        assert front._done["r1"].rank == 2
        # a zombie reply from the dead rank arrives late: first wins
        dead.complete(Completion(uid="r1", tokens=[9, 9], prompt_len=3,
                                 rank=1))
        assert front.poll_responses() == []
        assert front._done["r1"].rank == 2
        front.stop_fleet()
        assert dead.stopped() and live.stopped()
    finally:
        server.stop()
