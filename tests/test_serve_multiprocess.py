"""Serving-plane acceptance with real worker processes (ISSUE 11).

Repeats the chaos matrix's ``serve_kill_replica`` cell fast-tier: three
replica processes serve a KV-queue fleet, rank 2 is killed at its 5th
decode step mid-generation, and the cell passes only if the survivors
absorb the traffic with ZERO lost requests, the dead replica's
in-flight work was really redistributed, and the merged flight-recorder
postmortem names the dead rank.

Unlike the training cells this needs no native transport — the serving
plane rides the rendezvous HTTP KV store alone — so the cell runs (and
the invariant holds) on any host that can spawn processes.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.chaos_matrix import SCENARIOS, run_scenario  # noqa: E402


def test_serve_kill_replica_cell():
    result = run_scenario("serve_kill_replica",
                          SCENARIOS["serve_kill_replica"])
    assert result["ok"], json.dumps(result, indent=2)

    frontend = result["results"][0]
    assert frontend["zero_lost"]
    assert frontend["completed"] == frontend["submitted"]
    # the kill landed mid-generation: work really moved, and the victim
    # (16 tokens per request, dead at decode step 5) completed nothing
    assert frontend["requeued"] > 0
    # the victim is declared dead; a survivor may ALSO appear here
    # transiently (heartbeats ride a dedicated thread so compiles can't
    # lapse them, but a scheduler stall still can) — that only causes a
    # deduplicated re-dispatch
    assert 2 in frontend["dead_ranks"]
    assert 2 not in frontend["served_by"]
    assert len(frontend["served_by"]) >= 1
    assert result["exit_codes"][2] == 21      # the injected exit code
    # postmortem culprit attribution (require_culprit already enforced
    # inside run_scenario; pin the cell's config against drift too)
    spec = SCENARIOS["serve_kill_replica"]
    assert spec["require_culprit"] == 2
    assert spec["require_true"] == ["zero_lost", "requeued"]
