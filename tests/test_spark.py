"""Spark integration tests — service-level, no cluster (reference:
test/test_spark.py runs local-mode happy path + failure modes with stubs;
here the driver/task protocol is exercised over real TCP without pyspark).
"""

import os
import threading

import pytest

from horovod_tpu.run import util


@pytest.fixture(autouse=True)
def _isolate_environ():
    """The task mapper sets the worker env contract (HOROVOD_RANK/...)
    in os.environ — correct inside a Spark executor, but it must not leak
    into later tests in this process."""
    saved = dict(os.environ)
    yield
    os.environ.clear()
    os.environ.update(saved)
from horovod_tpu.run.service import ServiceClient
from horovod_tpu.spark import (
    RegisterSparkTaskRequest,
    SparkDriverService,
    SparkResultRequest,
    SparkTaskInfoRequest,
    _make_mapper,
    run,
)


class TestSparkDriverService:
    def test_protocol_and_allocation(self):
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=4)
        try:
            addr = ("127.0.0.1", driver.port)

            # four tasks on two "hosts" register out of order
            hashes = ["hostB", "hostA", "hostB", "hostA"]
            for index in (2, 0, 3, 1):
                c = ServiceClient(addr, key)
                c.call(RegisterSparkTaskRequest(index, hashes[index],
                                                "127.0.0.1"))
            assert driver.all_registered.wait(5)

            # no env before allocation
            c = ServiceClient(addr, key)
            assert c.call(SparkTaskInfoRequest(0)).env is None

            index_to_rank = driver.allocate({"EXTRA": "1"})
            assert sorted(index_to_rank) == [0, 1, 2, 3]
            assert sorted(index_to_rank.values()) == [0, 1, 2, 3]

            # first-registered host hash hosts rank 0... host order is by
            # lowest task index: index 0 is hostB -> hostB gets ranks 0,1
            env0 = c.call(SparkTaskInfoRequest(0)).env
            assert env0["HOROVOD_RANK"] == str(index_to_rank[0])
            assert env0["HOROVOD_SIZE"] == "4"
            assert env0["HOROVOD_LOCAL_SIZE"] == "2"
            assert env0["EXTRA"] == "1"
            assert env0["HOROVOD_CONTROLLER"] == "socket"
            # ranks on the same host hash are contiguous
            ranks_b = sorted(index_to_rank[i] for i in (0, 2))
            ranks_a = sorted(index_to_rank[i] for i in (1, 3))
            assert ranks_b == [0, 1] and ranks_a == [2, 3]

            # results flow
            for index in range(4):
                c.call(SparkResultRequest(index, True,
                                          util.dumps_base64(index * 10)))
            assert driver.all_results.wait(5)
            results = driver.results()
            assert util.loads_base64(results[2][1]) == 20
        finally:
            driver.shutdown()

    def test_mapper_end_to_end(self):
        """The task-side mapper against a live driver service."""
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=2)
        try:
            addr = ("127.0.0.1", driver.port)

            def fn(x):
                import os

                return (os.environ["HOROVOD_RANK"], x)

            mapper = _make_mapper([addr], key, fn, (7,), None,
                                  start_timeout=20.0)

            def task(index):
                list(mapper(index, iter(())))

            threads = [threading.Thread(target=task, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            assert driver.all_registered.wait(10)
            index_to_rank = driver.allocate({})
            assert driver.all_results.wait(10)
            for t in threads:
                t.join(5)

            results = driver.results()
            for index, (ok, payload) in results.items():
                assert ok
                rank_str, x = util.loads_base64(payload)
                assert int(rank_str) == index_to_rank[index]
                assert x == 7
        finally:
            driver.shutdown()

    def test_mapper_reports_failure(self):
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=1)
        try:
            addr = ("127.0.0.1", driver.port)

            def fn():
                raise ValueError("boom")

            mapper = _make_mapper([addr], key, fn, (), None,
                                  start_timeout=20.0)

            def task():
                with pytest.raises(ValueError):
                    list(mapper(0, iter(())))

            t = threading.Thread(target=task)
            t.start()
            assert driver.all_registered.wait(10)
            driver.allocate({})
            assert driver.all_results.wait(10)
            t.join(5)
            ok, payload = driver.results()[0]
            assert not ok and "boom" in payload
        finally:
            driver.shutdown()


class TestSparkRun:
    def test_requires_pyspark(self):
        with pytest.raises(RuntimeError, match="pyspark"):
            run(lambda: None, num_proc=1)


class TestSparkRetrySafety:
    def test_reregistration_after_allocation_rejected(self):
        """A Spark task retry arriving after ranks are fixed must fail the
        job loudly, not silently rejoin with a stale environment."""
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=2)
        try:
            addr = ("127.0.0.1", driver.port)
            for index in range(2):
                ServiceClient(addr, key).call(
                    RegisterSparkTaskRequest(index, f"h{index}",
                                             "127.0.0.1", 30000 + index))
            assert driver.all_registered.wait(5)
            driver.allocate({})
            with pytest.raises(RuntimeError, match="re-registered"):
                ServiceClient(addr, key).call(
                    RegisterSparkTaskRequest(0, "h0", "127.0.0.1", 30000))
        finally:
            driver.shutdown()

    def test_preallocation_retry_overwrites(self):
        """Before ranks are allocated, a Spark retry may harmlessly
        re-register — the latest registration (its real host) wins."""
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=2)
        try:
            addr = ("127.0.0.1", driver.port)
            ServiceClient(addr, key).call(
                RegisterSparkTaskRequest(0, "h0", "127.0.0.1", 30000))
            ServiceClient(addr, key).call(
                RegisterSparkTaskRequest(0, "h0-retry", "127.0.0.1", 30001))
            ServiceClient(addr, key).call(
                RegisterSparkTaskRequest(1, "h1", "127.0.0.1", 30002))
            assert driver.all_registered.wait(5)
            driver.allocate({})
            env0 = ServiceClient(addr, key).call(SparkTaskInfoRequest(0)).env
            assert env0["HOROVOD_HOSTNAME"] == "h0-retry"
        finally:
            driver.shutdown()

    def test_coord_port_comes_from_rank0_task(self):
        key = util.make_secret_key()
        driver = SparkDriverService(key, num_proc=2)
        try:
            addr = ("127.0.0.1", driver.port)
            ServiceClient(addr, key).call(
                RegisterSparkTaskRequest(0, "hA", "10.0.0.5", 41234))
            ServiceClient(addr, key).call(
                RegisterSparkTaskRequest(1, "hB", "10.0.0.6", 45678))
            assert driver.all_registered.wait(5)
            driver.allocate({})
            env0 = ServiceClient(addr, key).call(SparkTaskInfoRequest(0)).env
            # rank 0 lives on the first-registered host; its own probed
            # port (and its routable IP) become the coordinator address
            assert env0["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "41234"
            assert env0["HOROVOD_GLOO_RENDEZVOUS_ADDR"] == "10.0.0.5"
        finally:
            driver.shutdown()


# ---------------------------------------------------------------------------
# Real pyspark local-mode integration (reference: test/test_spark.py:51-103
# runs horovod.spark.run on a local-mode SparkContext). Skipped LOUDLY when
# pyspark is not installed — install pyspark to activate.
# ---------------------------------------------------------------------------

try:
    import pyspark as _pyspark  # noqa: F401
    _HAVE_PYSPARK = True
except ImportError:
    _HAVE_PYSPARK = False

pyspark_required = pytest.mark.skipif(
    not _HAVE_PYSPARK,
    reason="SKIPPING real-pyspark integration: pyspark not installed "
           "(pip install pyspark to run horovod_tpu.spark.run end-to-end)")


def _spark_train_fn():
    """Runs inside each Spark python worker: init, one collective, report."""
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as _np

    import horovod_tpu as hvd

    hvd.init()
    out = hvd.synchronize(hvd.allreduce_async(
        _np.full((2,), float(hvd.rank() + 1), _np.float32),
        name="spark/x", average=False))
    rank, size = hvd.rank(), hvd.size()
    hvd.shutdown()
    return rank, size, float(out[0])


def _spark_failing_fn():
    import os as _os

    import horovod_tpu as hvd

    if int(_os.environ["HOROVOD_RANK"]) == 1:
        raise ValueError("injected task failure")
    hvd.init()
    hvd.shutdown()
    return "ok"


@pyspark_required
class TestRealPyspark:
    @pytest.fixture()
    def spark(self):
        from pyspark.sql import SparkSession

        os.environ["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep + os.environ.get("PYTHONPATH", ""))
        session = (SparkSession.builder.master("local[2]")
                   .appName("horovod_tpu-test")
                   .config("spark.ui.enabled", "false")
                   .getOrCreate())
        yield session
        session.stop()

    def test_run_happy_path(self, spark):
        results = run(_spark_train_fn, num_proc=2, start_timeout=120)
        assert [r[:2] for r in results] == [(0, 2), (1, 2)]
        # sum over ranks of (rank + 1) = 3, bit-exact on both ranks
        assert [r[2] for r in results] == [3.0, 3.0]

    def test_run_task_failure_raises(self, spark):
        with pytest.raises(RuntimeError, match="injected task failure"):
            run(_spark_failing_fn, num_proc=2, start_timeout=120)

    def test_run_timeout_when_undersubscribed(self, spark):
        # local[2] can only run 2 concurrent tasks; 4 ranks never fully
        # register and the start timeout names the capacity problem
        # (reference: test_spark.py timeout path)
        with pytest.raises(TimeoutError, match="task slots|register"):
            run(_spark_train_fn, num_proc=4, start_timeout=10)
