"""Sparse/embedding gradient path: allgather exchange == dense allreduce.

Mirrors the reference's sparse coverage (reference:
test/test_tensorflow.py allgather tests + the IndexedSlices path in
horovod/tensorflow/__init__.py:64-75): the sparse exchange must be
numerically identical to densify-then-allreduce, across jit styles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

VOCAB, DIM = 32, 8


def _batch(rng, n):
    ids = rng.randint(0, VOCAB, (n, 4)).astype(np.int32)
    labels = rng.rand(n, 4, DIM).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(labels)


def _loss(rows, labels):
    return jnp.mean((rows - labels) ** 2)


class TestSparseGrad:
    def test_densify_scatter_adds_duplicates(self, hvd_flat):
        sg = hvd_flat.SparseGrad(
            jnp.array([1, 1, 3]), jnp.ones((3, DIM)), VOCAB)
        dense = sg.densify()
        assert dense.shape == (VOCAB, DIM)
        np.testing.assert_allclose(dense[1], 2.0 * np.ones(DIM))
        np.testing.assert_allclose(dense[3], np.ones(DIM))
        assert float(jnp.abs(dense[0]).max()) == 0.0

    def test_with_sparse_embedding_grad_matches_dense_grad(self, hvd_flat):
        rng = np.random.RandomState(0)
        table = jnp.asarray(rng.rand(VOCAB, DIM).astype(np.float32))
        ids, labels = _batch(rng, 2)

        def dense_loss(table):
            rows = jnp.take(table, ids.reshape(-1), axis=0).reshape(
                ids.shape + (DIM,))
            return _loss(rows, labels)

        value, sg = hvd_flat.with_sparse_embedding_grad(_loss)(
            table, ids, labels)
        dense_ref = jax.grad(dense_loss)(table)
        np.testing.assert_allclose(np.asarray(sg.densify()),
                                   np.asarray(dense_ref), atol=1e-6)
        np.testing.assert_allclose(float(value), float(dense_loss(table)),
                                   rtol=1e-6)

    def test_shard_map_exchange_matches_dense_allreduce(self, hvd):
        """allgather-exchange == pmean(densify) inside shard_map."""
        rng = np.random.RandomState(1)
        table = jnp.asarray(rng.rand(VOCAB, DIM).astype(np.float32))
        ids, labels = _batch(rng, 16)  # 2 rows per device on the 2x4 mesh

        def per_device(table, ids, labels):
            _, sg = hvd.with_sparse_embedding_grad(_loss)(
                table, ids, labels)
            sparse_avg = hvd.allreduce_gradients((sg,))[0]
            dense_avg = hvd.allreduce_gradients((sg.densify(),))[0]
            return sparse_avg, dense_avg

        f = jax.jit(jax.shard_map(
            per_device, mesh=hvd.mesh(),
            in_specs=(P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
            out_specs=(P(), P()), check_vma=False))
        sparse_avg, dense_avg = f(table, ids, labels)
        np.testing.assert_allclose(np.asarray(sparse_avg),
                                   np.asarray(dense_avg), atol=1e-6)

    def test_sparse_as_dense_matches(self, hvd):
        rng = np.random.RandomState(2)
        table = jnp.asarray(rng.rand(VOCAB, DIM).astype(np.float32))
        ids, labels = _batch(rng, 16)

        def per_device(table, ids, labels):
            _, sg = hvd.with_sparse_embedding_grad(_loss)(
                table, ids, labels)
            a = hvd.allreduce_gradients((sg,), sparse_as_dense=True)[0]
            b = hvd.allreduce_gradients((sg,), sparse_as_dense=False)[0]
            return a, b

        f = jax.jit(jax.shard_map(
            per_device, mesh=hvd.mesh(),
            in_specs=(P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
            out_specs=(P(), P()), check_vma=False))
        a, b = f(table, ids, labels)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_distributed_optimizer_trains_embedding(self, hvd):
        """End-to-end: DistributedOptimizer consumes SparseGrad leaves;
        training on the sparse path tracks the dense path exactly and the
        loss decreases."""
        rng = np.random.RandomState(3)
        table0 = jnp.zeros((VOCAB, DIM), jnp.float32)
        ids, labels = _batch(rng, 16)
        opt = hvd.DistributedOptimizer(optax.sgd(0.5))

        def make_step(densify):
            def per_device(table, opt_state, ids, labels):
                loss, sg = hvd.with_sparse_embedding_grad(_loss)(
                    table, ids, labels)
                g = sg.densify() if densify else sg
                updates, opt_state = opt.update(g, opt_state, table)
                return loss, optax.apply_updates(table, updates), opt_state

            return jax.jit(jax.shard_map(
                per_device, mesh=hvd.mesh(),
                in_specs=(P(), P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
                out_specs=(P(), P(), P()), check_vma=False))

        results = {}
        for densify in (False, True):
            step = make_step(densify)
            table, opt_state = table0, opt.init(table0)
            losses = []
            for _ in range(10):
                loss, table, opt_state = step(table, opt_state, ids, labels)
                losses.append(float(loss))
            results[densify] = (np.asarray(table), losses)
        assert results[False][1][-1] < results[False][1][0]
        np.testing.assert_allclose(results[False][0], results[True][0],
                                   atol=1e-6)

    def test_eager_sparse_exchange(self, hvd):
        """Worker-stacked eager SparseGrad averages like the dense path."""
        n = hvd.size()
        idx = hvd.stack_per_worker(
            [np.array([w % VOCAB, (w + 1) % VOCAB], np.int32)
             for w in range(n)])
        val = hvd.stack_per_worker(
            [np.full((2, DIM), float(w + 1), np.float32) for w in range(n)])
        sg = hvd.SparseGrad(idx, val, VOCAB)
        out = hvd.allreduce_gradients((sg,))[0]
        assert out.shape == (VOCAB, DIM)

        expect = np.zeros((VOCAB, DIM), np.float32)
        for w in range(n):
            expect[w % VOCAB] += w + 1
            expect[(w + 1) % VOCAB] += w + 1
        expect /= n
        np.testing.assert_allclose(np.asarray(out), expect, atol=1e-6)

    def test_global_batch_pjit_sparse(self, hvd):
        """Under plain jit (no bound axes) the sparse grad densifies
        without an extra division."""
        rng = np.random.RandomState(4)
        table = jnp.asarray(rng.rand(VOCAB, DIM).astype(np.float32))
        ids, labels = _batch(rng, 8)

        @jax.jit
        def f(table, ids, labels):
            _, sg = hvd.with_sparse_embedding_grad(_loss)(
                table, ids, labels)
            return hvd.allreduce_gradients((sg,))[0]

        def dense_loss(table):
            rows = jnp.take(table, ids.reshape(-1), axis=0).reshape(
                ids.shape + (DIM,))
            return _loss(rows, labels)

        np.testing.assert_allclose(np.asarray(f(table, ids, labels)),
                                   np.asarray(jax.grad(dense_loss)(table)),
                                   atol=1e-6)

    def test_sparse_with_gradient_accumulation(self, hvd):
        """backward_passes_per_step > 1 densifies SparseGrad leaves before
        MultiSteps accumulation; two accumulated sparse micro-steps equal
        one dense step on the summed gradient."""
        rng = np.random.RandomState(5)
        table0 = jnp.asarray(rng.rand(VOCAB, DIM).astype(np.float32))
        opt = hvd.DistributedOptimizer(optax.sgd(0.1),
                                       backward_passes_per_step=2)

        def micro(table, opt_state, ids, labels):
            _, sg = hvd.with_sparse_embedding_grad(_loss)(table, ids, labels)
            updates, opt_state = opt.update(sg, opt_state, table)
            return optax.apply_updates(table, updates), opt_state

        step = jax.jit(jax.shard_map(
            micro, mesh=hvd.mesh(),
            in_specs=(P(), P(), P(hvd.GLOBAL_AXES), P(hvd.GLOBAL_AXES)),
            out_specs=(P(), P()), check_vma=False))

        ids1, labels1 = _batch(rng, 16)
        ids2, labels2 = _batch(rng, 16)
        table, opt_state = table0, opt.init(table0)
        table, opt_state = step(table, opt_state, ids1, labels1)
        np.testing.assert_allclose(np.asarray(table), np.asarray(table0),
                                   atol=1e-7)  # first micro-step: no update
        table, opt_state = step(table, opt_state, ids2, labels2)
        assert np.abs(np.asarray(table) - np.asarray(table0)).max() > 1e-6
