"""TensorFlow binding tests — eager ops on the virtual 8-device world.

Port of the core of the reference's TF test strategy (reference:
test/test_tensorflow.py:60-240 — op correctness over dtypes/dims, grad
registrations, error cases; run there under mpirun, here on the
single-controller 8-device world where every "rank" holds the same
replicated host value, so allreduce(average) is identity, allgather
tiles, broadcast is identity). True cross-rank semantics (distinct
per-rank values) run under the launcher in
test_multiprocess.py::test_tensorflow_binding_across_processes.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as tfhvd  # noqa: E402


@pytest.fixture
def hvd_tf(hvd):
    """The shared 2x4 world, surfaced through the TF binding (same
    process-global state; the fixture's init/shutdown applies)."""
    return tfhvd


def test_allreduce_dtypes_and_dims(hvd_tf):
    """reference: test_tensorflow.py test_horovod_allreduce_cpu —
    dtype x dimension sweep."""
    for dtype in (tf.float32, tf.float64, tf.int32, tf.int64,
                  tf.bfloat16):
        for dim in (1, 2, 3):
            shape = (2,) * dim
            x = tf.cast(tf.fill(shape, 3), dtype)
            out = hvd_tf.allreduce(x, average=False)
            want = np.full(shape, 3 * hvd_tf.size())
            np.testing.assert_allclose(
                np.asarray(out.numpy(), dtype=np.float64), want)
            assert out.dtype == dtype


_TF_DTYPES = [tf.uint8, tf.int8, tf.int16, tf.int32, tf.int64,
              tf.float16, tf.bfloat16, tf.float32, tf.float64]


@pytest.mark.parametrize("dtype", _TF_DTYPES, ids=lambda d: d.name)
def test_dtype_matrix(hvd_tf, dtype):
    """Reference-breadth dtype x op matrix (r5; reference:
    test_tensorflow.py:152-649 sweeps every op per dtype): allreduce /
    allgather / broadcast / reducescatter / alltoall, with 64-bit
    payloads that corrupt if the data plane narrows them (the x32-jax
    hazard _to_plane guards)."""
    w = hvd_tf.size()
    big = (1 << 40) if dtype in (tf.int64, tf.float64) else 0
    x = tf.cast(tf.reshape(tf.range(w * 2 * 3) % 7 + 1 + big,
                           (w * 2, 3)), dtype)
    xn = x.numpy().astype(np.float64)
    out = hvd_tf.allreduce(x, average=False)
    assert out.dtype == dtype
    np.testing.assert_array_equal(out.numpy().astype(np.float64), xn * w)
    out = hvd_tf.allgather(x)
    assert out.dtype == dtype and out.shape == (w * w * 2, 3)
    np.testing.assert_array_equal(out.numpy().astype(np.float64),
                                  np.tile(xn, (w, 1)))
    out = hvd_tf.broadcast(x, root_rank=0)
    assert out.dtype == dtype
    np.testing.assert_array_equal(out.numpy().astype(np.float64), xn)
    out = hvd_tf.reducescatter(x, op=tfhvd.Sum)
    assert out.dtype == dtype and out.shape == (2, 3)
    np.testing.assert_array_equal(out.numpy().astype(np.float64),
                                  xn[:2] * w)
    out = hvd_tf.alltoall(x)
    assert out.dtype == dtype and out.shape == x.shape
    np.testing.assert_array_equal(out.numpy().astype(np.float64),
                                  np.tile(xn[:2], (w, 1)))


@pytest.mark.parametrize("dtype", [tf.int32, tf.int64, tf.float32,
                                   tf.float64], ids=lambda d: d.name)
def test_fused_many_small_per_dtype(hvd_tf, dtype):
    """grouped_allreduce burst per dtype — many small tensors negotiated
    and fused in one enqueue burst (reference: test_tensorflow.py fused
    many-small sweeps)."""
    big = (1 << 40) if dtype in (tf.int64, tf.float64) else 0
    tensors = [tf.cast(tf.fill([4], big + i), dtype) for i in range(12)]
    outs = hvd_tf.grouped_allreduce(tensors, op=tfhvd.Sum)
    for i, o in enumerate(outs):
        assert o.dtype == dtype
        np.testing.assert_array_equal(
            o.numpy().astype(np.float64),
            np.full(4, float(big + i) * hvd_tf.size()))


@pytest.mark.parametrize("dtype", _TF_DTYPES, ids=lambda d: d.name)
def test_variable_size_allgather_per_dtype(hvd_tf, dtype):
    """Variable-size (ragged dim 0) allgather per dtype rides the
    negotiated recvcounts path (reference: test_tensorflow.py
    test_horovod_allgather_variable_size). The single-controller world
    is replicated, so the ragged-ACROSS-RANKS case lives in the np=2/3
    dtype_matrix scenario (tests/mp_worker.py); here each dtype's
    tiling + dtype preservation is pinned on an uneven dim 0."""
    w = hvd_tf.size()
    big = (1 << 40) if dtype in (tf.int64, tf.float64) else 0
    x = tf.cast(tf.reshape(tf.range(5 * 2) % 7 + 1 + big, (5, 2)), dtype)
    out = hvd_tf.allgather(x)
    assert out.dtype == dtype and out.shape == (5 * w, 2)
    np.testing.assert_array_equal(
        out.numpy().astype(np.float64),
        np.tile(x.numpy().astype(np.float64), (w, 1)))


def test_reducescatter_grad(hvd_tf):
    """grad(reducescatter-sum) = allgather(grad): each rank's input
    slice j feeds shard j on its owner, so the incoming shard gradient
    tiles back to the full input."""
    w = hvd_tf.size()
    x = tf.Variable(tf.ones([w * 2, 3]))
    with tf.GradientTape() as tape:
        y = hvd_tf.reducescatter(x, op=tfhvd.Sum)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, x)
    # replicated world: allgather(ones shard) tiles ones over dim 0
    np.testing.assert_allclose(g.numpy(), np.ones((w * 2, 3)))


def test_alltoall_grad(hvd_tf):
    """alltoall is its own adjoint: grad(alltoall) = alltoall(grad)."""
    w = hvd_tf.size()
    x = tf.Variable(tf.ones([w * 2, 3]))
    with tf.GradientTape() as tape:
        y = hvd_tf.alltoall(x)
        loss = tf.reduce_sum(y * 2.0)
    g = tape.gradient(loss, x)
    np.testing.assert_allclose(g.numpy(), np.full((w * 2, 3), 2.0))


def test_reducescatter_indivisible_raises(hvd_tf):
    with pytest.raises(ValueError, match="divide evenly"):
        hvd_tf.reducescatter(tf.ones([hvd_tf.size() * 2 + 1, 3]))


def test_allreduce_average_replicated_identity(hvd_tf):
    x = tf.constant([1.5, -2.5, 0.0])
    out = hvd_tf.allreduce(x, average=True)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)


def test_allgather_tiles_replicated(hvd_tf):
    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    out = hvd_tf.allgather(x)
    assert out.shape == (2 * hvd_tf.size(), 3)
    np.testing.assert_allclose(out.numpy(),
                               np.tile(x.numpy(), (hvd_tf.size(), 1)))


def test_broadcast_identity_and_grad(hvd_tf):
    """reference: test_horovod_broadcast_grad — grad is summed on root,
    zero elsewhere; on the single-controller world this process IS the
    root, so grad = world * ones."""
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd_tf.broadcast(v, root_rank=0))
    g = tape.gradient(y, v)
    np.testing.assert_allclose(g.numpy(), [hvd_tf.size()] * 2)


def test_allreduce_grad(hvd_tf):
    """reference: test_horovod_allreduce_grad — grad(sum-allreduce) is a
    sum-allreduce of the upstream grad."""
    v = tf.Variable([1.0, 2.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd_tf._allreduce(v))
    g = tape.gradient(y, v)
    np.testing.assert_allclose(g.numpy(), [hvd_tf.size()] * 2)


def test_allgather_grad(hvd_tf):
    """reference: test_horovod_allgather_grad — grad is this rank's
    slice of the summed grad."""
    v = tf.Variable([[1.0, 2.0], [3.0, 4.0]])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd_tf.allgather(v) ** 2)
    g = tape.gradient(y, v)
    # d/dv sum(gathered^2): each replica contributes 2v; summed over the
    # world then sliced back = world * 2v
    np.testing.assert_allclose(g.numpy(), hvd_tf.size() * 2 * v.numpy())


def test_indexed_slices_allreduce(hvd_tf):
    s = tf.IndexedSlices(tf.constant([[1.0, 2.0]]), tf.constant([3]),
                         tf.constant([10, 2]))
    out = hvd_tf.allreduce(s, average=True)
    assert isinstance(out, tf.IndexedSlices)
    assert out.values.shape[0] == hvd_tf.size()
    np.testing.assert_allclose(out.values.numpy()[0],
                               [1.0 / hvd_tf.size(), 2.0 / hvd_tf.size()])


def test_compression_fp16_roundtrip(hvd_tf):
    """reference: test_compression.py — fp16 halves the wire dtype and
    restores; ints pass through."""
    x = tf.constant([1.5, 2.5, -3.0])
    out = hvd_tf.allreduce(x, average=True,
                           compression=hvd_tf.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-3)
    xi = tf.constant([1, 2, 3])
    out = hvd_tf.allreduce(xi, average=False,
                           compression=hvd_tf.Compression.fp16)
    assert out.dtype == tf.int32


def test_distributed_gradient_tape(hvd_tf):
    v = tf.Variable([2.0, 3.0])
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(v * v)
    dtape = hvd_tf.DistributedGradientTape(tape)
    grads = dtape.gradient(loss, [v])
    np.testing.assert_allclose(grads[0].numpy(), [4.0, 6.0], rtol=1e-6)


def test_distributed_optimizer_keras(hvd_tf):
    v = tf.Variable([1.0, 2.0])
    opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.5))
    opt.apply_gradients([(tf.constant([2.0, 2.0]), v)])
    np.testing.assert_allclose(v.numpy(), [0.0, 1.0], rtol=1e-6)
    # a REAL Keras optimizer subclass: isinstance holds (model.compile
    # accepts it) and attribute writes hit real optimizer state
    assert isinstance(opt, tf.keras.optimizers.Optimizer)
    opt.learning_rate = 0.125
    assert float(opt.learning_rate) == 0.125


def test_distributed_optimizer_keras_model_compile(hvd_tf):
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(2, input_shape=(3,))])
    opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="mse")
    x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = np.zeros((8, 2), np.float32)
    model.fit(x, y, epochs=1, verbose=0)


def test_integer_average_rejected(hvd_tf):
    """int / size would silently promote to float64; the reference
    rejects integer averaging instead."""
    with pytest.raises(ValueError, match="integer"):
        hvd_tf.allreduce(tf.constant([2, 4, 6]), average=True)
    out = hvd_tf.allreduce(tf.constant([2, 4, 6]), average=False)
    assert out.dtype == tf.int32


def test_grads_fn_names_are_stable(hvd_tf):
    """Re-wrapping the tape each step (the common usage) must reuse the
    same closure and wire names — fresh auto-names would defeat the
    response cache and re-negotiate every step."""
    from horovod_tpu.tensorflow import mpi_ops

    v = tf.Variable([2.0, 3.0])

    def one_step():
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        dtape = hvd_tf.DistributedGradientTape(tape)
        return dtape.gradient(loss, [v])

    one_step()
    before = dict(mpi_ops._op_counters)
    for _ in range(3):
        one_step()
    # explicit stable names bypass the noname counters entirely
    assert dict(mpi_ops._op_counters) == before


def test_distributed_optimizer_legacy(hvd_tf):
    """The tf.compat.v1 path: compute_gradients allreduces (reference:
    __init__.py:245-259)."""
    v = tf.Variable([1.0, 2.0])
    opt = hvd_tf.DistributedOptimizer(
        tf.compat.v1.train.GradientDescentOptimizer(0.5))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(v * v)
    # eager compute_gradients path needs a callable loss in TF2
    grads_and_vars = opt.compute_gradients(
        lambda: tf.reduce_sum(v * v), var_list=[v])
    grads = [g for g, _ in grads_and_vars]
    np.testing.assert_allclose(grads[0].numpy(), [2.0, 4.0], rtol=1e-6)


def test_broadcast_variables(hvd_tf):
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0]])
    hvd_tf.broadcast_variables([v1, v2], root_rank=0)
    np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])
    np.testing.assert_allclose(v2.numpy(), [[3.0]])


def test_broadcast_variables_64bit_exact(hvd_tf):
    """int64 step counters >= 2^31 and float64 values must round-trip
    EXACTLY — the x32 JAX data plane would silently narrow them, so
    they travel as int32 bit pairs."""
    big = 2**40 + 12345
    v_step = tf.Variable(np.int64(big))
    v_f64 = tf.Variable(np.float64(1.0 + 2**-40))
    hvd_tf.broadcast_variables([v_step, v_f64], root_rank=0)
    assert int(v_step.numpy()) == big
    assert float(v_f64.numpy()) == 1.0 + 2**-40


def test_broadcast_global_variables_raises_eager(hvd_tf):
    with pytest.raises(RuntimeError, match="eager execution"):
        hvd_tf.broadcast_global_variables(0)


def test_broadcast_variables_graph_mode(hvd_tf):
    """Graph-mode broadcast_variables returns a runnable op (VERDICT r3
    ask 4: the former shim crashed on var.numpy()). Replicated world ->
    identity values, but the whole graph machinery (py_function bridge,
    64-bit bit-pair path, assigns) executes for real."""
    g = tf.Graph()
    with g.as_default():
        assert not tf.executing_eagerly()
        v = tf.compat.v1.get_variable(
            "v", initializer=np.asarray([1.5, -2.0], np.float32))
        step = tf.compat.v1.get_variable(
            "step", initializer=np.int64(2**40 + 7), dtype=tf.int64)
        op = hvd_tf.broadcast_variables([v, step], root_rank=0)
        with tf.compat.v1.Session() as sess:
            sess.run(tf.compat.v1.global_variables_initializer())
            sess.run(op)
            got_v, got_step = sess.run([v, step])
    np.testing.assert_allclose(got_v, [1.5, -2.0])
    assert int(got_step) == 2**40 + 7


def test_broadcast_global_variables_hook_monitored_session(hvd_tf):
    """BroadcastGlobalVariablesHook under MonitoredTrainingSession — the
    reference's estimator/TF1 integration point (reference:
    horovod/tensorflow/__init__.py:158-192)."""
    g = tf.Graph()
    with g.as_default():
        w = tf.compat.v1.get_variable(
            "w", initializer=np.full((2, 2), 3.0, np.float32))
        hook = hvd_tf.BroadcastGlobalVariablesHook(root_rank=0)
        with tf.compat.v1.train.MonitoredTrainingSession(
                hooks=[hook]) as sess:
            got = sess.run(w)
    np.testing.assert_allclose(got, np.full((2, 2), 3.0))


def test_ops_inside_tf_function(hvd_tf):
    calls = []

    @tf.function
    def step(z):
        calls.append(1)
        return hvd_tf.allreduce(z, average=False)

    out = step(tf.constant([2.0]))
    np.testing.assert_allclose(out.numpy(), [2.0 * hvd_tf.size()])
    out = step(tf.constant([5.0]))  # second call reuses the trace
    np.testing.assert_allclose(out.numpy(), [5.0 * hvd_tf.size()])
    assert len(calls) == 1


def test_keras_binding_fit_callbacks_and_reload(hvd_tf, tmp_path):
    """The tf.keras sub-binding end-to-end (reference:
    horovod/tensorflow/keras + _keras/callbacks.py): DistributedOptimizer
    under model.fit, broadcast + metric-average + LR-warmup callbacks,
    rank-0 save and rewrapping load_model."""
    import horovod_tpu.tensorflow.keras as hvd_keras

    rng = np.random.RandomState(0)
    x = rng.rand(128, 8).astype(np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int64)
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(16, activation="relu", input_shape=(8,)),
        tf.keras.layers.Dense(2),
    ])
    opt = hvd_keras.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss=tf.keras.losses.
                  SparseCategoricalCrossentropy(from_logits=True),
                  metrics=["accuracy"])
    steps = 128 // 32
    history = model.fit(
        x, y, batch_size=32, epochs=3, verbose=0,
        callbacks=[
            hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_keras.callbacks.MetricAverageCallback(),
            hvd_keras.callbacks.LearningRateWarmupCallback(
                warmup_epochs=2, steps_per_epoch=steps),
        ])
    assert history.history["loss"][-1] < history.history["loss"][0]
    # warmup ramps toward the base LR by the end of epoch 2
    assert history.history["lr"][-1] > history.history["lr"][0] / 10

    path = str(tmp_path / "model.keras")
    model.save(path)
    restored = hvd_keras.load_model(path)
    assert type(restored.optimizer).__name__ == "DistributedSGD"
    np.testing.assert_allclose(
        model.predict(x[:4], verbose=0),
        restored.predict(x[:4], verbose=0), rtol=1e-6)


def test_keras_value_helpers(hvd_tf):
    import horovod_tpu.tensorflow.keras as hvd_keras

    out = hvd_keras.allreduce(np.asarray([2.0, 4.0], np.float32),
                              average=True)
    np.testing.assert_allclose(out, [2.0, 4.0])
    out = hvd_keras.broadcast(np.asarray([1.0], np.float32), 0)
    np.testing.assert_allclose(out, [1.0])
    g = hvd_keras.allgather(np.ones((2, 2), np.float32))
    assert g.shape == (2 * hvd_keras.size(), 2)


def test_lifecycle_surface(hvd_tf):
    assert hvd_tf.size() == 8
    assert hvd_tf.rank() == 0
    assert hvd_tf.is_initialized()
    assert hvd_tf.xla_built()
    assert not hvd_tf.mpi_built()
    assert hvd_tf.gloo_enabled() == hvd_tf.gloo_built()
