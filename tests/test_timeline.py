"""Timeline tests (reference: test/test_timeline.py — runs with
HOROVOD_TIMELINE set and asserts valid JSON with negotiate/op phases)."""

import json

from horovod_tpu.timeline import Timeline


def test_timeline_writes_valid_json(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    tl.negotiate_start("grad_0", "ALLREDUCE")
    tl.negotiate_rank_ready("grad_0", 0)
    tl.negotiate_rank_ready("grad_0", 1)
    tl.negotiate_end("grad_0")
    tl.start("grad_0", "ALLREDUCE")
    tl.activity_start("grad_0", "XLA_COLLECTIVE")
    tl.activity_end("grad_0")
    tl.end("grad_0")
    tl.close()

    events = json.load(open(path))
    names = [e.get("name") for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "ALLREDUCE" in names
    assert "XLA_COLLECTIVE" in names
    assert "RANK_0_READY" in names
    # metadata event naming the tensor's pseudo-process
    meta = [e for e in events if e.get("ph") == "M"]
    assert meta and meta[0]["args"]["name"] == "grad_0"


def test_timeline_cycle_markers(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = Timeline(path, mark_cycles=True)
    tl.mark_cycle_start()
    tl.mark_cycle_start()
    tl.close()
    events = json.load(open(path))
    cycles = [e for e in events if str(e.get("name", "")).startswith("CYCLE_")]
    assert len(cycles) == 2


def test_timeline_via_init_env(tmp_path, monkeypatch):
    import horovod_tpu as hvd
    from horovod_tpu.core import state

    hvd.shutdown()
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    hvd.init(mesh_shape=(1, 8))
    assert state.global_state().timeline is not None
    hvd.shutdown()
    events = json.load(open(path))
    assert isinstance(events, list)


def test_native_writer_used_and_escapes(tmp_path):
    """The C++ SPSC writer (cpp/timeline.cc) is the active backend when
    the native library builds, and its JSON escaping is correct."""
    from horovod_tpu.runtime.native import native_built
    from horovod_tpu.timeline import _make_writer, _NativeWriter

    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    if native_built():
        assert isinstance(tl._writer, _NativeWriter)
    tl.start('weird"name\\x', "ALL\"RED\\UCE")
    tl.end('weird"name\\x')
    tl.close()
    events = json.load(open(path))
    assert any(e.get("name") == "ALL\"RED\\UCE" for e in events)


def test_native_writer_stress_many_events(tmp_path):
    """Thousands of events survive the ring (or are counted as dropped)."""
    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    for i in range(5000):
        tl.start(f"t{i % 7}", "ALLREDUCE")
        tl.end(f"t{i % 7}")
    tl.close()
    events = json.load(open(path))
    dropped = sum(e["args"]["count"] for e in events
                  if e.get("name") == "dropped_events")
    starts = sum(1 for e in events if e.get("ph") == "B")
    assert starts + dropped >= 5000


def test_timeline_epoch_clock_domain(tmp_path):
    """Events are stamped in epoch microseconds so traces from different
    ranks/producers interleave truthfully when merged."""
    import time

    path = str(tmp_path / "trace.json")
    before_us = time.time_ns() / 1e3
    tl = Timeline(path)
    tl.start("t", "ALLREDUCE")
    tl.end("t")
    tl.close()
    after_us = time.time_ns() / 1e3
    events = [e for e in json.load(open(path)) if e.get("ph") == "B"]
    assert events and before_us <= events[0]["ts"] <= after_us


def test_merge_traces(tmp_path):
    """tpurun --merge-trace: per-rank timelines + a gzipped device-style
    trace become one Chrome trace with disjoint pid ranges and preserved
    epoch timestamps (reference: one host+device trace, timeline.cc)."""
    import gzip

    from horovod_tpu.timeline import merge_traces

    r0, r1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    for path, tensor in [(r0, "grad/a"), (r1, "grad/b")]:
        tl = Timeline(path)
        tl.negotiate_start(tensor, "ALLREDUCE")
        tl.negotiate_end(tensor)
        tl.close()
    # a device-side trace in the object format, gzipped (what TensorBoard's
    # profile export produces)
    dev = str(tmp_path / "device.json.gz")
    with gzip.open(dev, "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "X", "pid": 7, "tid": 0, "ts": 1.0, "dur": 5.0,
             "name": "fusion.1"}]}, f)

    out = str(tmp_path / "merged.json")
    n = merge_traces(out, [r0, r1, dev])
    merged = json.load(open(out))["traceEvents"]
    assert len(merged) == n
    names = {e.get("name") for e in merged}
    assert "NEGOTIATE_ALLREDUCE" in names and "fusion.1" in names
    # each input's pids got a source-file label and a private pid range
    label_events = [e for e in merged
                    if e.get("ph") == "M" and e.get("name") == "process_labels"]
    assert {e["args"]["labels"] for e in label_events} == {
        "[r0.json]", "[r1.json]", "[device.json.gz]"}
    by_name = {e.get("name"): e for e in merged}
    # the device event's label sits on the device event's OWN pid
    dev_pid = by_name["fusion.1"]["pid"]
    assert any(e["pid"] == dev_pid and e["args"]["labels"] ==
               "[device.json.gz]" for e in label_events)
    assert by_name["NEGOTIATE_ALLREDUCE"]["pid"] != dev_pid


def test_merge_traces_align_and_truncated(tmp_path):
    """--merge-trace-align rebases each input to a common origin; a
    truncated array from a crashed writer still loads."""
    from horovod_tpu.timeline import merge_traces

    a = str(tmp_path / "a.json")
    with open(a, "w") as f:  # truncated: no closing bracket
        f.write('[\n{"ph": "B", "pid": 1, "ts": 1000.0, "name": "x"},\n')
    b = str(tmp_path / "b.json")
    json.dump([{"ph": "B", "pid": 1, "ts": 5555.0, "name": "y"}],
              open(b, "w"))
    out = str(tmp_path / "m.json")
    merge_traces(out, [a, b], align=True)
    merged = json.load(open(out))["traceEvents"]
    by_name = {e.get("name"): e for e in merged if e.get("ph") == "B"}
    assert by_name["x"]["ts"] == 0.0 and by_name["y"]["ts"] == 0.0


def test_timeline_counters_emit_c_events(tmp_path):
    """Timeline.counters writes Chrome "C" counter events on pid 0 with a
    shared timestamp (the per-cycle metrics overlay)."""
    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    tl.counters({"queue_depth": 3, "cache_hits": 7})
    tl.counters({"queue_depth": 0, "cache_hits": 9})
    tl.close()
    events = json.load(open(path))
    counters = [e for e in events if e.get("ph") == "C"]
    assert len(counters) == 4
    assert all(e["pid"] == 0 for e in counters)
    depth = [e["args"]["value"] for e in counters
             if e["name"] == "queue_depth"]
    assert depth == [3, 0]
    # both series in one counters() call share one timestamp
    first_two = [e["ts"] for e in counters[:2]]
    assert first_two[0] == first_two[1]


def test_merge_traces_preserves_counter_events(tmp_path):
    """Merged "C" events survive with remapped pids: two ranks' counter
    overlays land on distinct pids and keep their values."""
    from horovod_tpu.timeline import merge_traces

    r0, r1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    for path, depth in [(r0, 5), (r1, 11)]:
        tl = Timeline(path)
        tl.negotiate_start("g", "ALLREDUCE")
        tl.negotiate_end("g")
        tl.counters({"queue_depth": depth})
        tl.close()
    out = str(tmp_path / "merged.json")
    merge_traces(out, [r0, r1])
    merged = json.load(open(out))["traceEvents"]
    counters = [e for e in merged if e.get("ph") == "C"]
    assert sorted(e["args"]["value"] for e in counters) == [5, 11]
    # pid remapping kept the two ranks' counter series distinct
    assert len({e["pid"] for e in counters}) == 2
    # and each counter pid carries its source-file label
    labels = {e["pid"]: e["args"]["labels"] for e in merged
              if e.get("ph") == "M" and e.get("name") == "process_labels"}
    srcs = {labels[e["pid"]] for e in counters}
    assert srcs == {"[r0.json]", "[r1.json]"}


def test_python_writer_flushes_without_close(tmp_path):
    """The pure-Python writer flushes after the queue drains, so a live
    (never-closed) trace is readable mid-run — and loadable through the
    truncated-array tolerance."""
    import time as _time

    from horovod_tpu.timeline import _Writer, _load_trace_events

    path = str(tmp_path / "live.json")
    w = _Writer(path)
    w.emit("B", 1, 123.0, name="live_event")
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        if "live_event" in open(path).read():
            break
        _time.sleep(0.05)
    events = _load_trace_events(path)
    assert any(e.get("name") == "live_event" for e in events)
    w.close()


def test_python_writer_counts_drops_when_unhealthy(tmp_path):
    """Events emitted after the writer goes unhealthy are counted, and
    the count shows up in hvd.metrics()."""
    import horovod_tpu as hvd
    from horovod_tpu.timeline import _Writer

    path = str(tmp_path / "t.json")
    w = _Writer(path)
    w.close()  # writer thread exits; _healthy goes False
    before = hvd.metrics()["horovod_timeline_dropped_events_total"][
        "values"][0]["value"]
    w.emit("B", 1, 1.0, name="late")
    w.emit("E", 1, 2.0)
    after = hvd.metrics()["horovod_timeline_dropped_events_total"][
        "values"][0]["value"]
    assert after - before == 2
