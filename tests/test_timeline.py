"""Timeline tests (reference: test/test_timeline.py — runs with
HOROVOD_TIMELINE set and asserts valid JSON with negotiate/op phases)."""

import json

from horovod_tpu.timeline import Timeline


def test_timeline_writes_valid_json(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    tl.negotiate_start("grad_0", "ALLREDUCE")
    tl.negotiate_rank_ready("grad_0", 0)
    tl.negotiate_rank_ready("grad_0", 1)
    tl.negotiate_end("grad_0")
    tl.start("grad_0", "ALLREDUCE")
    tl.activity_start("grad_0", "XLA_COLLECTIVE")
    tl.activity_end("grad_0")
    tl.end("grad_0")
    tl.close()

    events = json.load(open(path))
    names = [e.get("name") for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "ALLREDUCE" in names
    assert "XLA_COLLECTIVE" in names
    assert "RANK_0_READY" in names
    # metadata event naming the tensor's pseudo-process
    meta = [e for e in events if e.get("ph") == "M"]
    assert meta and meta[0]["args"]["name"] == "grad_0"


def test_timeline_cycle_markers(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = Timeline(path, mark_cycles=True)
    tl.mark_cycle_start()
    tl.mark_cycle_start()
    tl.close()
    events = json.load(open(path))
    cycles = [e for e in events if str(e.get("name", "")).startswith("CYCLE_")]
    assert len(cycles) == 2


def test_timeline_via_init_env(tmp_path, monkeypatch):
    import horovod_tpu as hvd
    from horovod_tpu.core import state

    hvd.shutdown()
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    hvd.init(mesh_shape=(1, 8))
    assert state.global_state().timeline is not None
    hvd.shutdown()
    events = json.load(open(path))
    assert isinstance(events, list)


def test_native_writer_used_and_escapes(tmp_path):
    """The C++ SPSC writer (cpp/timeline.cc) is the active backend when
    the native library builds, and its JSON escaping is correct."""
    from horovod_tpu.runtime.native import native_built
    from horovod_tpu.timeline import _make_writer, _NativeWriter

    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    if native_built():
        assert isinstance(tl._writer, _NativeWriter)
    tl.start('weird"name\\x', "ALL\"RED\\UCE")
    tl.end('weird"name\\x')
    tl.close()
    events = json.load(open(path))
    assert any(e.get("name") == "ALL\"RED\\UCE" for e in events)


def test_native_writer_stress_many_events(tmp_path):
    """Thousands of events survive the ring (or are counted as dropped)."""
    path = str(tmp_path / "trace.json")
    tl = Timeline(path)
    for i in range(5000):
        tl.start(f"t{i % 7}", "ALLREDUCE")
        tl.end(f"t{i % 7}")
    tl.close()
    events = json.load(open(path))
    dropped = sum(e["args"]["count"] for e in events
                  if e.get("name") == "dropped_events")
    starts = sum(1 for e in events if e.get("ph") == "B")
    assert starts + dropped >= 5000
