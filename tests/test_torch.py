"""Torch binding tests — collective semantics, autograd, optimizer.

Mirrors the reference's torch op-test structure (reference:
test/test_torch.py:1-1382): collective results asserted against locally
computed expectations, gradient correctness per op, optimizer wrapper
behavior (hooks, synchronize, zero_grad race guard), and parameter /
optimizer-state broadcast.

World model: one process owning the 8-device CPU mesh = 8 workers holding
identical (replicated) values, so average is identity and sum multiplies by
world size — the single-controller invariant. The true multi-process torch
path (distinct per-rank values over the socket controller) is exercised by
test_multiprocess.py's torch scenario.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd  # noqa: E402

WORLD = 8


@pytest.fixture(autouse=True)
def _world():
    hvd.shutdown()
    hvd.init(mesh_shape=(1, WORLD))
    yield
    hvd.shutdown()


class TestOps:
    def test_allreduce_average_identity(self):
        x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        out = hvd.allreduce(x)
        assert torch.allclose(out, x)
        assert out is not x

    def test_allreduce_inplace(self):
        x = torch.ones(4)
        out = hvd.allreduce_(x)
        assert out is x
        assert torch.allclose(out, torch.ones(4))

    def test_allreduce_sum_dtypes(self):
        for dtype in [torch.float32, torch.float64, torch.float16,
                      torch.bfloat16, torch.int32, torch.int64]:
            x = torch.ones(5, dtype=dtype)
            out = hvd.synchronize(hvd.allreduce_async(x, average=False))
            assert out.dtype == dtype, dtype
            assert torch.equal(out, x * WORLD), dtype

    ALL_DTYPES = [torch.uint8, torch.int8, torch.int16, torch.int32,
                  torch.int64, torch.float16, torch.bfloat16,
                  torch.float32, torch.float64]

    @pytest.mark.parametrize(
        "dtype", ALL_DTYPES, ids=lambda d: str(d).split(".")[-1])
    def test_dtype_matrix(self, dtype):
        """Reference-breadth dtype x op matrix (r5; reference:
        test/test_torch.py sweeps every op across uint8..fp64). 64-bit
        payloads carry values that corrupt if anything narrows to
        32-bit on the data plane (the x32-jax hazard _to_plane guards)."""
        big = (1 << 40) if dtype in (torch.int64, torch.float64) else 0
        # position-dependent values (catch chunk-ordering bugs), plus a
        # beyond-32-bit offset for the 64-bit dtypes
        x = (torch.arange(WORLD * 2 * 3).reshape(WORLD * 2, 3) % 7
             + 1 + big).to(dtype)
        # allreduce sum: 8 identical workers
        out = hvd.allreduce(x, average=False)
        assert out.dtype == dtype
        assert torch.equal(out, x * WORLD), dtype
        # allgather tiles the replicated tensor
        out = hvd.allgather(x)
        assert out.dtype == dtype and out.shape == (WORLD * WORLD * 2, 3)
        assert torch.equal(out, x.repeat(WORLD, 1))
        # broadcast identity
        out = hvd.broadcast(x, root_rank=0)
        assert out.dtype == dtype
        assert torch.equal(out, x)
        # reducescatter sum: worker 0's shard of 8x
        out = hvd.reducescatter(x, op=hvd.Sum)
        assert out.dtype == dtype and out.shape == (2, 3)
        assert torch.equal(out, x[:2] * WORLD), dtype
        # reducescatter min of identical copies is the shard itself
        out = hvd.reducescatter(x, op=hvd.Min)
        assert torch.equal(out, x[:2])
        # alltoall: worker 0 receives chunk 0 from all 8 identical workers
        out = hvd.alltoall(x)
        assert out.dtype == dtype and out.shape == x.shape
        assert torch.equal(out, x[:2].repeat(WORLD, 1))

    @pytest.mark.parametrize(
        "dtype", [torch.int32, torch.int64, torch.float32, torch.float64],
        ids=lambda d: str(d).split(".")[-1])
    def test_fused_many_small_per_dtype(self, dtype):
        """Many small async ops enqueued before any synchronize — the
        runtime negotiates and fuses the burst (reference:
        test_tensorflow.py fused many-small sweeps)."""
        big = (1 << 40) if dtype in (torch.int64, torch.float64) else 0
        handles = [
            hvd.allreduce_async(
                torch.full((4,), big + i, dtype=dtype), average=False,
                name=f"torch_fuse/{str(dtype)}/{i}")
            for i in range(12)]
        for i, h in enumerate(handles):
            out = hvd.synchronize(h)
            assert out.dtype == dtype
            assert torch.equal(
                out, torch.full((4,), (big + i) * WORLD, dtype=dtype)), i

    def test_reducescatter_default_op_is_average(self):
        """Omitted op means Average on EVERY surface (core _resolve_op,
        torch, tf) — a binding defaulting to Sum would silently return
        world-times-larger results to migrating code (r5 review)."""
        x = torch.full((WORLD * 2, 3), 4.0)
        out = hvd.reducescatter(x)  # avg of identical copies = the shard
        assert torch.equal(out, x[:2])

    def test_reducescatter_indivisible_raises(self):
        with pytest.raises(ValueError, match="divide evenly"):
            hvd.reducescatter(torch.ones(WORLD * 2 + 1, 3))

    def test_alltoall_indivisible_raises(self):
        with pytest.raises(ValueError, match="divide evenly"):
            hvd.alltoall(torch.ones(WORLD + 3, 2))

    def test_allreduce_fp16_compression(self):
        x = torch.full((8,), 2.0)
        out = hvd.allreduce(x, compression=hvd.Compression.fp16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, x)

    def test_allreduce_bf16_compression(self):
        x = torch.full((8,), 2.0)
        out = hvd.allreduce(x, compression=hvd.Compression.bf16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, x)

    def test_allgather(self):
        x = torch.randn(3, 2)
        out = hvd.allgather(x)
        assert out.shape == (3 * WORLD, 2)
        assert torch.allclose(out, x.repeat(WORLD, 1))

    def test_broadcast(self):
        x = torch.randn(4)
        out = hvd.broadcast(x, root_rank=0)
        assert torch.allclose(out, x)

    def test_poll_synchronize(self):
        h = hvd.allreduce_async(torch.ones(3))
        out = hvd.synchronize(h)
        assert hvd.poll(h)
        assert torch.allclose(out, torch.ones(3))

    def test_allreduce_grad(self):
        x = torch.randn(5, requires_grad=True)
        out = hvd.allreduce(x)
        out.sum().backward()
        assert torch.allclose(x.grad, torch.ones(5))

    def test_allgather_grad(self):
        # Each of the WORLD (identical) workers computes the same loss over
        # the gathered output; the distributed gradient is the sum-allreduce
        # of grad_output sliced to this worker's segment → WORLD * ones.
        x = torch.randn(3, 2, requires_grad=True)
        out = hvd.allgather(x)
        out.sum().backward()
        assert torch.allclose(x.grad, torch.full((3, 2), float(WORLD)))

    def test_broadcast_grad(self):
        # rank 0 is the root, so it receives the summed gradient.
        x = torch.randn(4, requires_grad=True)
        out = hvd.broadcast(x, root_rank=0)
        (out * 2).sum().backward()
        assert torch.allclose(x.grad, torch.full((4,), 2.0 * WORLD))


class TestDistributedOptimizer:
    def _model(self):
        torch.manual_seed(0)
        return torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))

    def test_step_updates(self):
        model = self._model()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        before = [p.clone() for p in model.parameters()]
        loss = model(torch.randn(16, 4)).pow(2).mean()
        loss.backward()
        opt.step()
        after = list(model.parameters())
        assert any(not torch.allclose(b, a)
                   for b, a in zip(before, after))

    def test_matches_undistributed_sgd(self):
        # With replicated workers, averaged grads == local grads, so the
        # wrapped optimizer must reproduce plain SGD exactly.
        model_a, model_b = self._model(), self._model()
        model_b.load_state_dict(model_a.state_dict())
        opt_a = torch.optim.SGD(model_a.parameters(), lr=0.1)
        opt_b = hvd.DistributedOptimizer(
            torch.optim.SGD(model_b.parameters(), lr=0.1),
            named_parameters=model_b.named_parameters())
        x = torch.randn(8, 4)
        for opt, model in [(opt_a, model_a), (opt_b, model_b)]:
            model(x).pow(2).mean().backward()
            opt.step()
        for pa, pb in zip(model_a.parameters(), model_b.parameters()):
            assert torch.allclose(pa, pb, atol=1e-6)

    def test_zero_grad_race_guard(self):
        # reference: torch/__init__.py:197-202 — zero_grad between backward
        # and step must raise while async handles are outstanding.
        model = self._model()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        opt._handles[next(model.parameters())] = object()
        with pytest.raises(AssertionError, match="race"):
            opt.zero_grad()
        opt._handles.clear()

    def test_duplicate_names_rejected(self):
        model = self._model()
        params = list(model.named_parameters())
        params[1] = (params[0][0], params[1][1])
        with pytest.raises(ValueError, match="unique"):
            hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=params)

    def test_backward_passes_per_step_accumulates(self):
        model = self._model()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        x = torch.randn(4, 4)
        model(x).pow(2).mean().backward()
        # after one backward pass no allreduce has fired yet
        assert not opt._handles
        model(x).pow(2).mean().backward()
        assert opt._handles
        opt.step()

    def test_skip_synchronize(self):
        model = self._model()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        loss = model(torch.randn(4, 4)).pow(2).mean()
        loss.backward()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()


class TestBroadcastState:
    def test_broadcast_parameters_state_dict(self):
        model = torch.nn.Linear(3, 3)
        want = {k: v.clone() for k, v in model.state_dict().items()}
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.allclose(v, want[k])

    def test_broadcast_parameters_named(self):
        model = torch.nn.Linear(3, 3)
        hvd.broadcast_parameters(model.named_parameters(), root_rank=0)

    def test_broadcast_optimizer_state(self):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        model(torch.randn(2, 3)).sum().backward()
        opt.step()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        state = opt.state_dict()["state"]
        assert any("momentum_buffer" in s for s in state.values())

    def test_broadcast_optimizer_state_adam(self):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        model(torch.randn(2, 3)).sum().backward()
        opt.step()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        state = opt.state_dict()["state"]
        assert any("exp_avg" in s for s in state.values())

    def test_broadcast_object(self):
        assert hvd.broadcast_object({"epoch": 3}) == {"epoch": 3}

    def test_lbfgs_rejected(self):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.LBFGS(model.parameters())
        with pytest.raises(ValueError, match="LBFGS"):
            hvd.broadcast_optimizer_state(opt)


class TestNumpyBridge:
    def test_bf16_roundtrip(self):
        from horovod_tpu.torch.mpi_ops import _from_numpy, _to_numpy

        x = torch.randn(7).to(torch.bfloat16)
        arr = _to_numpy(x)
        back = _from_numpy(arr, x)
        assert back.dtype == torch.bfloat16
        assert torch.equal(back, x)

    def test_noncontiguous(self):
        from horovod_tpu.torch.mpi_ops import _to_numpy

        x = torch.randn(4, 4).t()
        arr = _to_numpy(x)
        np.testing.assert_allclose(arr, x.numpy())
