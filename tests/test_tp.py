"""Tensor parallelism: Megatron-sharded transformer training via GSPMD.

TPU-first extension (the reference is DP-only); correctness bar: TP
training must be numerically identical to unsharded training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models.transformer import Transformer, causal_lm_loss


def _model(hvd):
    return Transformer(vocab_size=64, d_model=32, num_layers=2, num_heads=4,
                       d_ff=64, max_seq=16, causal=True, dtype=jnp.float32,
                       attention_fn=hvd.xla_attention)


class TestTensorParallel:
    def test_rules_shard_expected_params(self, hvd):
        model = _model(hvd)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16), jnp.int32),
                            train=False)["params"]
        sh = hvd.params_shardings(params, hvd.mesh(),
                                  hvd.transformer_tp_rules("local"))
        flat = {jax.tree_util.keystr(p): s for p, s in
                jax.tree_util.tree_flatten_with_path(sh)[0]}

        def spec_of(key):
            (k,) = [v for kk, v in flat.items() if key in kk]
            return k.spec

        assert spec_of("layer_0']['attention']['query']['kernel") == \
            P(None, "local", None)
        assert spec_of("layer_0']['mlp']['wi']['kernel") == P(None, "local")
        assert spec_of("layer_0']['mlp']['wo']['kernel") == P("local", None)
        assert spec_of("token_embed") == P("local", None)
        # non-matching params replicate
        assert spec_of("final_norm']['scale") == P()

    def test_tp_training_matches_unsharded(self, hvd):
        """Two training steps under TP(local) x DP(cross) == two steps
        unsharded — GSPMD sharding must not change the math."""
        model = _model(hvd)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens,
                            train=False)["params"]
        # sgd: updates are linear in gradients, so sharded-reduction-order
        # noise stays O(eps) instead of being amplified like adam's
        # g/sqrt(v) at tiny v
        opt = optax.sgd(0.1, momentum=0.9)

        # --- unsharded reference ---
        ref_params = params
        ref_opt = opt.init(ref_params)

        @jax.jit
        def ref_step(p, s, x):
            loss, grads = jax.value_and_grad(lambda p: causal_lm_loss(
                model.apply({"params": p}, x, train=True), x))(p)
            updates, s = opt.update(grads, s, p)
            return loss, optax.apply_updates(p, updates), s

        # --- TP x DP ---
        placed, step, batch_sharding = hvd.tp_train_step(
            model, opt, params, hvd.transformer_tp_rules("local"),
            loss_fn=causal_lm_loss, batch_axis="cross", donate=False)
        tp_opt = opt.init(placed)
        xb = jax.device_put(tokens, batch_sharding)

        ref_losses, tp_losses = [], []
        tp_params, tp_state = placed, tp_opt
        for _ in range(2):
            rl, ref_params, ref_opt = ref_step(ref_params, ref_opt, tokens)
            tl, tp_params, _, tp_state = step(tp_params, {}, tp_state,
                                              xb, xb)
            ref_losses.append(float(rl))
            tp_losses.append(float(tl))
        np.testing.assert_allclose(tp_losses, ref_losses, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                        jax.tree_util.tree_leaves(tp_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_tp_params_actually_distributed(self, hvd):
        """Sharded leaves occupy 1/N of each device's memory."""
        model = _model(hvd)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16), jnp.int32),
                            train=False)["params"]
        opt = optax.sgd(0.1)
        placed, step, _ = hvd.tp_train_step(
            model, opt, params, hvd.transformer_tp_rules("local"),
            loss_fn=causal_lm_loss, donate=False)
        wi = placed["layer_0"]["mlp"]["wi"]["kernel"]
        n_local = hvd.mesh().shape["local"]
        shard = wi.addressable_shards[0]
        assert shard.data.shape == (32, 64 // n_local)
