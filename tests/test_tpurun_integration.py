"""Launcher binary end-to-end: `bin/tpurun -np 2` as a real subprocess
(the delta over test_run.py's in-process run_commandline coverage), with
tests/mp_worker.py as the 2-rank workload (reference: the Docker test
images bake `mpirun -np 2 -H localhost:2` as the canonical integration
drive, Dockerfile.test.cpu:53-83)."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.runtime.native import native_built

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


@pytest.mark.parametrize("extra_args", [["--no-jax-distributed"], []],
                         ids=["socket-controller", "jax-distributed"])
def test_tpurun_binary_two_ranks(extra_args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "collectives"],
        capture_output=True, text=True, timeout=240, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", [["--no-jax-distributed"], []],
                         ids=["socket-controller", "jax-distributed"])
def test_tpurun_kitchen_sink(extra_args):
    """Named + unnamed + broadcast + ragged allgather interleaved with
    cache churn, in both launcher modes — the scenario that caught the
    multi-controller eager-dispatch ordering bug."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["HOROVOD_CACHE_CAPACITY"] = "6"
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "kitchen_sink"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", [["--no-jax-distributed"], []],
                         ids=["socket-controller", "jax-distributed"])
def test_tpurun_torch_sink(extra_args):
    """Torch hooks + accumulation + interleaved eager ops, both modes,
    with a final parameter-identity check across ranks."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "torch_sink"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", [["--no-jax-distributed"], []],
                         ids=["socket-controller", "jax-distributed"])
def test_tpurun_tensorflow2_mnist_example(extra_args):
    """The flagship TF2 example under the real launcher at np=2, both
    launch modes: tape averaging + broadcast_variables; the example
    asserts loss descent and cross-rank lockstep itself."""
    pytest.importorskip("tensorflow")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable,
         os.path.join(REPO, "examples", "tensorflow2_mnist.py"),
         "--steps", "12"],
        capture_output=True, text=True, timeout=420, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "lockstep OK" in result.stdout


def test_tpurun_bert_large_sparse_example():
    """BASELINE config #5's example under the real launcher: BERT-Large
    torch model (CI-sized layer count, full d_model/heads) with the
    sparse embedding allgather exchange; the example itself asserts the
    cross-rank lockstep invariant."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", "--no-jax-distributed", sys.executable,
         os.path.join(REPO, "examples", "pytorch_bert_large_sparse.py"),
         "--layers", "2", "--seq", "32", "--batch", "4", "--steps", "2"],
        capture_output=True, text=True, timeout=420, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "lockstep OK" in result.stdout


def test_tpurun_ring_attention_cross_process():
    """Sequence parallelism over a process-spanning mesh: ring attention's
    ppermute crosses real process boundaries and matches dense attention."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "ring_sp"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


def test_tpurun_pipeline_and_moe_cross_process():
    """GPipe ppermute and MoE all_to_all across real process boundaries."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "pp_ep_xproc"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


def test_tpurun_keras_trainer():
    """Keras-style Trainer fit/evaluate under the launcher's global mesh."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "keras"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


def test_tpurun_lane_misuse_raises():
    """A caller-thread global-mesh dispatch with named async ops in
    flight raises OrderedLaneError instead of the documented hang
    (VERDICT r1 #3; reference misuse-raises philosophy:
    tensor_queue.cc:26-29)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "lane_misuse"],
        capture_output=True, text=True, timeout=240, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


def test_tpurun_scaling_benchmark_8dev():
    """The exact scaling-efficiency command from docs/benchmarks.md on an
    8-device virtual world: one JSON line with imgs_per_sec / n_chips /
    scaling_efficiency, so the v5p recipe is load-and-go (VERDICT r1 #7;
    reference: docs/benchmarks.rst:16-64). Two launcher processes with 4
    virtual CPU devices each form the 8-device global mesh — same sharded
    path as -np 8, but only two compiles on the single-core CI box."""
    import json as json_mod

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    bench = os.path.join(REPO, "examples", "jax_synthetic_benchmark.py")
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, bench,
         "--model", "ResNet18", "--batch-size", "1", "--image-size", "32",
         "--num-warmup-batches", "0", "--num-batches-per-iter", "1",
         "--num-iters", "1", "--json", "--one-chip-rate", "100.0",
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=900, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    # tpurun prefixes worker stdout with "[rank]<stdout>: "
    json_lines = [l[l.index("{"):] for l in result.stdout.splitlines()
                  if '{"imgs_per_sec"' in l]
    assert json_lines, result.stdout
    payload = json_mod.loads(json_lines[-1])
    assert payload["n_chips"] == 8
    assert payload["imgs_per_sec"] > 0
    assert payload["scaling_efficiency"] is not None


def test_tpurun_jit_train_global_mesh():
    """Jitted train step over the jax.distributed global mesh with
    per-process data: gradient averaging must be real cross-process
    collectives (divergent parameters fail the in-worker check)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "jit_train"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
