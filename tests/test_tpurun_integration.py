"""Launcher binary end-to-end: `bin/tpurun -np 2` as a real subprocess
(the delta over test_run.py's in-process run_commandline coverage), with
tests/mp_worker.py as the 2-rank workload (reference: the Docker test
images bake `mpirun -np 2 -H localhost:2` as the canonical integration
drive, Dockerfile.test.cpu:53-83)."""

import os
import subprocess
import sys

import jax
import pytest

from horovod_tpu.runtime.native import native_built

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")

# The default (jax.distributed) launch mode forms a global mesh whose
# collectives are real cross-process XLA computations. The CPU backend
# rejects those with "INVALID_ARGUMENT: Multiprocess computations aren't
# implemented on the CPU backend", so on CPU-only boxes the jax-distributed
# variants can never pass — only the socket-controller data plane can.
_cpu_no_multiprocess = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="CPU backend does not implement multiprocess XLA computations")

# both launcher modes where the platform allows; socket-controller always
_LAUNCH_MODES = dict(
    argvalues=[["--no-jax-distributed"],
               pytest.param([], marks=_cpu_no_multiprocess)],
    ids=["socket-controller", "jax-distributed"])


@pytest.mark.parametrize("extra_args", **_LAUNCH_MODES)
def test_tpurun_binary_two_ranks(extra_args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "collectives"],
        capture_output=True, text=True, timeout=240, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", **_LAUNCH_MODES)
def test_tpurun_kitchen_sink(extra_args):
    """Named + unnamed + broadcast + ragged allgather interleaved with
    cache churn, in both launcher modes — the scenario that caught the
    multi-controller eager-dispatch ordering bug."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["HOROVOD_CACHE_CAPACITY"] = "6"
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "kitchen_sink"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", **_LAUNCH_MODES)
def test_tpurun_torch_sink(extra_args):
    """Torch hooks + accumulation + interleaved eager ops, both modes,
    with a final parameter-identity check across ranks."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable, WORKER, "torch_sink"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@pytest.mark.parametrize("extra_args", **_LAUNCH_MODES)
def test_tpurun_tensorflow2_mnist_example(extra_args):
    """The flagship TF2 example under the real launcher at np=2, both
    launch modes: tape averaging + broadcast_variables; the example
    asserts loss descent and cross-rank lockstep itself."""
    pytest.importorskip("tensorflow")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", *extra_args, sys.executable,
         os.path.join(REPO, "examples", "tensorflow2_mnist.py"),
         "--steps", "12"],
        capture_output=True, text=True, timeout=420, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "lockstep OK" in result.stdout


def test_tpurun_bert_large_sparse_example():
    """BASELINE config #5's example under the real launcher: BERT-Large
    torch model (CI-sized layer count, full d_model/heads) with the
    sparse embedding allgather exchange; the example itself asserts the
    cross-rank lockstep invariant."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", "--no-jax-distributed", sys.executable,
         os.path.join(REPO, "examples", "pytorch_bert_large_sparse.py"),
         "--layers", "2", "--seq", "32", "--batch", "4", "--steps", "2"],
        capture_output=True, text=True, timeout=420, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "lockstep OK" in result.stdout


@_cpu_no_multiprocess
def test_tpurun_bert_mlm_headline_recipe():
    """The r4 headline recipe (gathered MLM head + gradient
    accumulation, docs/perf_experiments.md) through the PUBLIC example
    under the real launcher at np=2: per-rank data shards; the scan
    sums local micro-grads and DistributedOptimizer allreduces ONCE in
    opt.update after the scan."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable,
         os.path.join(REPO, "examples", "jax_bert_mlm.py"),
         "--model", "tiny", "--seq", "16", "--batch-size", "2",
         "--steps", "3", "--gathered", "--accum", "2"],
        capture_output=True, text=True, timeout=420, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "mlm loss" in result.stdout


# ~250s on a single-core box (two np=8 launches, each rank paying the
# TF/torch import serially) — the dominant tier-1 wall-clock sink; lives
# in the slow tier with the other multiprocess soaks
@pytest.mark.slow
def test_tpurun_pod_soak_dress_rehearsal(tmp_path):
    """Pod dress rehearsal (VERDICT r3 ask 3): ONE launcher-driven np=8
    localhost job exercising the whole stack together — native wire,
    autotune on, per-rank timelines, torch + TF + JAX collectives
    interleaved, mid-run rank-0 checkpoint, HARD death (os._exit 137, no
    shutdown), then a resume run that restores step 5, continues to step
    10, and asserts a cross-surface lockstep digest. Afterwards the 8
    per-rank timelines must merge into one valid trace. Documented in
    docs/tpurun.md (Pod dress rehearsal)."""
    pytest.importorskip("tensorflow")
    pytest.importorskip("torch")
    import json

    soak_dir = str(tmp_path)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({"SOAK_DIR": soak_dir, "HOROVOD_AUTOTUNE": "1",
                "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
                "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "5",
                # force the NIC-discovery/task-agent path even though the
                # job is all-local — the dress rehearsal must walk the
                # same init a real pod does
                "HOROVOD_NIC_DISCOVERY": "1"})
    np_ranks = 8

    # run 1: train to step 5, checkpoint, die hard (preemption)
    r1 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", str(np_ranks), "--no-jax-distributed",
         sys.executable, WORKER, "pod_soak"],
        capture_output=True, text=True, timeout=900, env=env)
    assert r1.returncode != 0  # the job DIED; that is the point
    assert r1.stdout.count("CKPT_SAVED") == np_ranks, \
        r1.stdout + r1.stderr
    assert os.path.exists(os.path.join(soak_dir, "ckpt",
                                       "ckpt_5.msgpack"))

    # run 2: resume from the checkpoint, finish, lockstep
    env["SOAK_RESUME"] = "1"
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", str(np_ranks), "--no-jax-distributed",
         sys.executable, WORKER, "pod_soak"],
        capture_output=True, text=True, timeout=900, env=env)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert r2.stdout.count("SOAK_DONE") == np_ranks, \
        r2.stdout + r2.stderr

    # the resume run's per-rank timelines merge into one valid trace
    from horovod_tpu.timeline import merge_traces

    rank_files = [os.path.join(soak_dir, f"timeline.{r}.json")
                  for r in range(np_ranks)]
    for f in rank_files:
        assert os.path.exists(f), f
    merged = os.path.join(soak_dir, "merged.json")
    n = merge_traces(merged, rank_files)
    assert n > 0
    events = json.load(open(merged))["traceEvents"]
    pids = {e.get("pid") for e in events if e.get("ph") != "M"}
    assert len(pids) >= np_ranks, f"merged trace covers {len(pids)} ranks"


@_cpu_no_multiprocess
def test_tpurun_ring_attention_cross_process():
    """Sequence parallelism over a process-spanning mesh: ring attention's
    ppermute crosses real process boundaries and matches dense attention."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "ring_sp"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@_cpu_no_multiprocess
def test_tpurun_pipeline_and_moe_cross_process():
    """GPipe ppermute and MoE all_to_all across real process boundaries."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "pp_ep_xproc"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@_cpu_no_multiprocess
def test_tpurun_keras_trainer():
    """Keras-style Trainer fit/evaluate under the launcher's global mesh."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "keras"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@_cpu_no_multiprocess
def test_tpurun_lane_misuse_raises():
    """A caller-thread global-mesh dispatch with named async ops in
    flight raises OrderedLaneError instead of the documented hang
    (VERDICT r1 #3; reference misuse-raises philosophy:
    tensor_queue.cc:26-29)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "lane_misuse"],
        capture_output=True, text=True, timeout=240, env=env)
    assert result.returncode == 0, result.stdout + result.stderr


@_cpu_no_multiprocess
def test_tpurun_scaling_benchmark_8dev():
    """The exact scaling-efficiency command from docs/benchmarks.md on an
    8-device virtual world: one JSON line with imgs_per_sec / n_chips /
    scaling_efficiency, so the v5p recipe is load-and-go (VERDICT r1 #7;
    reference: docs/benchmarks.rst:16-64). Two launcher processes with 4
    virtual CPU devices each form the 8-device global mesh — same sharded
    path as -np 8, but only two compiles on the single-core CI box."""
    import json as json_mod

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    bench = os.path.join(REPO, "examples", "jax_synthetic_benchmark.py")
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, bench,
         "--model", "ResNet18", "--batch-size", "1", "--image-size", "32",
         "--num-warmup-batches", "0", "--num-batches-per-iter", "1",
         "--num-iters", "1", "--json", "--one-chip-rate", "100.0",
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=900, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
    # tpurun prefixes worker stdout with "[rank]<stdout>: "
    json_lines = [l[l.index("{"):] for l in result.stdout.splitlines()
                  if '{"imgs_per_sec"' in l]
    assert json_lines, result.stdout
    payload = json_mod.loads(json_lines[-1])
    assert payload["n_chips"] == 8
    assert payload["imgs_per_sec"] > 0
    assert payload["scaling_efficiency"] is not None


@_cpu_no_multiprocess
def test_tpurun_jit_train_global_mesh():
    """Jitted train step over the jax.distributed global mesh with
    per-process data: gradient averaging must be real cross-process
    collectives (divergent parameters fail the in-worker check)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "tpurun"),
         "-np", "2", sys.executable, WORKER, "jit_train"],
        capture_output=True, text=True, timeout=300, env=env)
    assert result.returncode == 0, result.stdout + result.stderr
