"""Unit coverage for the tracing + SLO plane (tracing.py; docs/tracing.md).

Pinned-down contracts:

* the span ring is bounded: capacity evicts oldest-first and the
  ``HOROVOD_TRACE`` grammar (off switch / capacity integer) holds;
* trace context survives the KV wire format round-trip on both
  ``Request`` and ``Completion``;
* burn-rate math: bad fraction over the rolling window divided by the
  allowed fraction, budget clamped at zero, ``ok=False`` scores only
  the availability objective;
* a burn-rate crossing emits exactly ONE ``slo_burn_rate`` flight event
  and re-arms when the rate falls back under the threshold;
* ``/slo`` and ``/healthz`` routes: readiness transitions (503 before
  init, 503 while serving without a replica heartbeat, 200 after);
* Chrome conversion + flow arrows: ``merge_profile_dir`` lays out
  per-rank request lanes on the ``/_time``-corrected clock and joins one
  trace_id's spans across lanes;
* the replica loop records queue_wait/prefill/decode_block/serve spans
  and scores the SLO tracker for every completion.

The 2-rank half (frontend process + a real ``python -m
horovod_tpu.serve`` replica, one trace_id across both ranks in the
merged Perfetto trace) is at the bottom.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu import flight_recorder, profiler, tracing
from horovod_tpu.serve.queue import Completion, Request, RequestQueue
from horovod_tpu.utils.env import (HOROVOD_SLO_AVAILABILITY,
                                   HOROVOD_SLO_LATENCY_MS,
                                   HOROVOD_SLO_TTFT_MS, HOROVOD_SLO_WINDOW,
                                   HOROVOD_TRACE, parse_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- span ring

def test_parse_trace_grammar():
    assert parse_trace(None) == (True, 4096)
    assert parse_trace("1") == (True, 4096)
    assert parse_trace("0") == (False, 4096)
    assert parse_trace("off") == (False, 4096)
    assert parse_trace("128") == (True, 128)


def test_span_ring_bounded_oldest_evicted(monkeypatch):
    monkeypatch.setenv(HOROVOD_TRACE, "16")
    t = tracing.Tracer()
    assert t.capacity == 16
    for i in range(40):
        t.record("s", t0=float(i), dur=0.001, trace_id="t%d" % i)
    spans = t.spans()
    assert len(spans) == 16
    # oldest evicted, newest kept, order preserved
    assert [s["trace_id"] for s in spans] == \
        ["t%d" % i for i in range(24, 40)]


def test_disabled_tracer_records_nothing(monkeypatch):
    monkeypatch.setenv(HOROVOD_TRACE, "0")
    t = tracing.Tracer()
    t.record("s", t0=0.0, dur=0.001)
    assert t.spans() == []


def test_new_trace_ids_unique_and_wire_sized():
    ids = {tracing.new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 16 for i in ids)


# ------------------------------------------------------- context on the wire

def test_trace_context_survives_kv_roundtrip():
    req = Request(uid="r1", prompt=[1, 2, 3], max_new_tokens=4,
                  trace_id="abcdef0123456789", requeues=2)
    back = Request.from_json(req.to_json())
    assert back.trace_id == "abcdef0123456789" and back.requeues == 2
    done = Completion(uid="r1", tokens=[5], prompt_len=3, rank=1,
                      trace_id="abcdef0123456789", requeues=2)
    back = Completion.from_json(done.to_json())
    assert back.trace_id == "abcdef0123456789" and back.requeues == 2


def test_pre_tracing_wire_format_still_parses():
    # a frontend from an older build omits the context fields entirely
    raw = json.dumps({"uid": "r1", "prompt": [1], "max_new_tokens": 2})
    req = Request.from_json(raw.encode())
    assert req.trace_id == "" and req.requeues == 0


def test_queue_submit_mints_trace_and_records_spans():
    q = RequestQueue()
    uid = q.submit([1, 2, 3], max_new_tokens=4)
    req = q.pull(rank=0, max_n=1)[0]
    assert req.trace_id                      # minted at submit
    q.complete(Completion(uid=uid, tokens=[5], prompt_len=3, rank=0,
                          trace_id=req.trace_id))
    names = [s["name"] for s in tracing.spans()
             if s.get("trace_id") == req.trace_id]
    assert "request.submit" in names and "request.response" in names


def test_eager_collective_records_span(hvd):
    """The eager single-controller dispatch (_op_event) lands on the same
    collective: lane as the enqueue runtime — a training script that never
    touches the runtime still gets comm spans."""
    import jax.numpy as jnp

    before = tracing.tracer().spans_recorded()
    hvd.allreduce(hvd.stack_per_worker(
        [jnp.ones(4) * (r + 1) for r in range(hvd.size())]),
        name="traced_probe")
    spans = [s for s in tracing.spans()
             if s["name"] == "collective:traced_probe"]
    assert spans, [s["name"] for s in tracing.spans()]
    assert spans[-1]["op"] == "allreduce" and spans[-1]["bytes"] > 0
    assert tracing.tracer().spans_recorded() > before


# ----------------------------------------------------------------- SLO math

def _slo_tracker(monkeypatch, *, window=10, availability=0.9,
                 latency_ms=100.0, ttft_ms=50.0, burn_alert=14.0):
    monkeypatch.setenv(HOROVOD_SLO_WINDOW, str(window))
    monkeypatch.setenv(HOROVOD_SLO_AVAILABILITY, str(availability))
    monkeypatch.setenv(HOROVOD_SLO_LATENCY_MS, str(latency_ms))
    monkeypatch.setenv(HOROVOD_SLO_TTFT_MS, str(ttft_ms))
    monkeypatch.setenv("HOROVOD_SLO_BURN_ALERT", str(burn_alert))
    return tracing.SLOTracker()


def test_burn_rate_math(monkeypatch):
    slo = _slo_tracker(monkeypatch)          # window 10, target 0.9
    for _ in range(9):
        slo.record_request(ttft_s=0.01, latency_s=0.05)
    assert slo.burn_rate("latency") == 0.0
    assert slo.error_budget_remaining("latency") == 1.0
    # one slow request in a 10-deep window: bad fraction 0.1, allowed
    # fraction 1 - 0.9 = 0.1 -> burn exactly 1.0, budget exhausted
    slo.record_request(ttft_s=0.01, latency_s=0.5)
    assert slo.burn_rate("latency") == pytest.approx(1.0)
    assert slo.error_budget_remaining("latency") == pytest.approx(0.0)
    # ttft stayed clean throughout
    assert slo.burn_rate("ttft") == 0.0
    st = slo.state()
    assert st["slo"]["latency"]["bad_total"] == 1
    assert st["requests_scored"] == 10


def test_budget_clamps_at_zero(monkeypatch):
    slo = _slo_tracker(monkeypatch)
    for _ in range(5):
        slo.record_request(ttft_s=0.01, latency_s=9.9)   # all bad
    assert slo.burn_rate("latency") > 1.0
    assert slo.error_budget_remaining("latency") == 0.0


def test_failed_request_scores_only_availability(monkeypatch):
    slo = _slo_tracker(monkeypatch)
    slo.record_request(0.0, 0.0, ok=False)
    st = slo.state()["slo"]
    assert st["availability"]["window_observed"] == 1
    assert st["availability"]["bad_total"] == 1
    assert st["ttft"]["window_observed"] == 0
    assert st["latency"]["window_observed"] == 0
    # an unserved request must not pollute the latency percentiles
    assert slo.state()["latency_ms_percentiles"]["p50"] is None


def test_burn_alert_emits_once_then_rearms(monkeypatch):
    # availability 0.5 -> allowed fraction 0.5; alert at burn >= 1.5,
    # i.e. bad fraction >= 0.75 of the window
    slo = _slo_tracker(monkeypatch, window=4, availability=0.5,
                       burn_alert=1.5)

    def alert_events():
        return [e for e in flight_recorder.recorder().events()
                if e.get("kind") == "slo_burn_rate"
                and e.get("objective") == "latency"]

    n0 = len(alert_events())
    for _ in range(4):
        slo.record_request(ttft_s=0.01, latency_s=9.9)
    assert len(alert_events()) == n0 + 1     # one crossing, one event
    slo.record_request(ttft_s=0.01, latency_s=9.9)
    assert len(alert_events()) == n0 + 1     # sustained burn: no storm
    assert slo.state()["slo"]["latency"]["alerting"]
    for _ in range(4):                       # window drains clean
        slo.record_request(ttft_s=0.01, latency_s=0.05)
    assert not slo.state()["slo"]["latency"]["alerting"]
    for _ in range(4):                       # re-crossing fires again
        slo.record_request(ttft_s=0.01, latency_s=9.9)
    assert len(alert_events()) == n0 + 2


def test_slow_request_exemplars_keep_the_slowest(monkeypatch):
    slo = _slo_tracker(monkeypatch, window=64, latency_ms=1e9, ttft_ms=1e9)
    for i in range(12):
        slo.record_request(
            ttft_s=0.01, latency_s=0.1 * (i + 1), trace_id="t%d" % i,
            phases={"queue_wait": 0.01, "decode": 0.09 * (i + 1)})
    ex = slo.state()["slow_request_exemplars"]
    assert len(ex) == 8                      # bounded
    assert ex[0]["trace_id"] == "t11"        # slowest first
    assert ex[0]["slowest_phase"] == "decode"
    assert ex[0]["latency_ms"] == pytest.approx(1200.0)
    lats = [e["latency_ms"] for e in ex]
    assert lats == sorted(lats, reverse=True)


def test_format_slo_report(monkeypatch):
    slo = _slo_tracker(monkeypatch)
    slo.record_request(ttft_s=0.01, latency_s=0.9, trace_id="deadbeef",
                       phases={"decode": 0.8})
    dumps = [{"launch_rank": 0, "state": {"slo": slo.state()}},
             {"launch_rank": 1, "state": {}}]     # pre-tracing dump
    report = tracing.format_slo_report(dumps)
    assert "=== SLO report ===" in report
    assert "rank 0" in report and "deadbeef" in report
    assert tracing.format_slo_report([{"state": {}}]) == ""


# ------------------------------------------------------------- HTTP routes

def test_healthz_and_slo_routes(monkeypatch):
    from horovod_tpu.metrics import registry

    port = registry().serve(0)

    def get(route):
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d%s" % (port, route),
                    timeout=5.0) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read().decode())

    monkeypatch.setattr(tracing, "_init_ready", False)
    monkeypatch.setattr(tracing, "_serve_started", False)
    monkeypatch.setattr(tracing, "_serve_heartbeat_seen", False)
    code, doc = get("/healthz")
    assert code == 503 and not doc["ready"]
    tracing.mark_initialized(True)
    code, doc = get("/healthz")
    assert code == 200 and doc["ready"]
    # serving without a live replica heartbeat: not ready for traffic
    tracing.note_serve_started()
    code, doc = get("/healthz")
    assert code == 503 and doc["serving"]
    tracing.note_replica_heartbeat()
    code, doc = get("/healthz")
    assert code == 200 and doc["first_replica_heartbeat"]

    code, doc = get("/slo")
    assert code == 200
    assert doc["schema"] == tracing.SCHEMA
    assert set(doc["slo"]) == {"ttft", "latency", "availability"}
    for rec in doc["slo"].values():
        assert 0.0 <= rec["error_budget_remaining"] <= 1.0


# --------------------------------------------- Chrome conversion + merging

def test_spans_to_chrome_shape():
    spans = [{"trace_id": "t1", "name": "request.prefill", "t": 100.0,
              "dur": 0.25, "rank": 1, "uid": "r1"},
             {"name": "bad", "t": "nan"}]      # malformed: skipped
    (ev,) = tracing.spans_to_chrome(spans)
    assert ev["ph"] == "X" and ev["cat"] == "request"
    assert ev["ts"] == pytest.approx(100.0 * 1e6)
    assert ev["dur"] == pytest.approx(0.25 * 1e6)
    assert ev["args"]["trace_id"] == "t1" and ev["args"]["uid"] == "r1"


def test_flow_events_join_multi_span_traces():
    anchors = [
        {"trace_id": "t1", "pid": 0, "tid": 2, "ts": 100.0, "dur": 5.0},
        {"trace_id": "t1", "pid": 4, "tid": 2, "ts": 200.0, "dur": 9.0},
        {"trace_id": "t1", "pid": 4, "tid": 2, "ts": 300.0, "dur": 1.0},
        {"trace_id": "solo", "pid": 0, "tid": 2, "ts": 50.0, "dur": 1.0},
    ]
    flows = tracing.flow_events(anchors)
    assert [f["ph"] for f in flows] == ["s", "t", "f"]   # solo: no flow
    start, step, fin = flows
    assert start["ts"] == pytest.approx(105.0)   # anchored at span END
    assert step["ts"] == pytest.approx(200.0)    # receipt at span start
    assert fin["bp"] == "e"
    assert {f["id"] for f in flows} == {"t1"}


def test_merge_profile_dir_corrects_clocks_and_draws_flows(tmp_path):
    """Two fake rank dumps with different /_time offsets: the merged
    trace must carry both request lanes on ONE corrected clock and join
    the shared trace_id with flow arrows."""
    trace_id = "feedface00000001"
    base = 1000.0
    dump0 = {"launch_rank": 0, "clock_offset_seconds": 0.0,
             "trace_events": [], "flight_events": [],
             "request_spans": [
                 {"trace_id": trace_id, "name": "request.submit",
                  "t": base, "dur": 0.001, "rank": 0}]}
    # rank 1's clock runs 0.5 s fast; its offset estimate corrects it
    dump1 = {"launch_rank": 1, "clock_offset_seconds": -0.5,
             "trace_events": [], "flight_events": [],
             "request_spans": [
                 {"trace_id": trace_id, "name": "request.serve",
                  "t": base + 0.6, "dur": 0.05, "rank": 1}]}
    for rank, dump in ((0, dump0), (1, dump1)):
        with open(tmp_path / f"profile-rank-{rank}.json", "w") as f:
            json.dump(dump, f)
    out, count = profiler.merge_profile_dir(str(tmp_path))
    with open(out) as f:
        merged = json.load(f)["traceEvents"]
    labels = [e["args"]["labels"] for e in merged
              if e.get("name") == "process_labels"]
    assert "rank 0 requests" in labels and "rank 1 requests" in labels
    xs = {e["name"]: e for e in merged
          if e.get("ph") == "X" and e.get("cat") == "request"}
    assert xs["request.submit"]["ts"] == pytest.approx(base * 1e6)
    # 1000.6 on rank 1's fast clock is 1000.1 on the corrected one
    assert xs["request.serve"]["ts"] == pytest.approx((base + 0.1) * 1e6)
    assert xs["request.submit"]["pid"] != xs["request.serve"]["pid"]
    flows = [e for e in merged if e.get("ph") in ("s", "t", "f")
             and e.get("id") == trace_id]
    assert [f["ph"] for f in sorted(flows, key=lambda f: f["ts"])] == \
        ["s", "f"]


# ------------------------------------------------------- replica lifecycle

def test_replica_records_lifecycle_spans_and_scores_slo(monkeypatch):
    from test_serve import _FakeEngine, _replica

    slo = _slo_tracker(monkeypatch, window=16, latency_ms=1e9, ttft_ms=1e9)
    monkeypatch.setattr(tracing, "_slo", slo)
    q = RequestQueue()
    uid = q.submit([1, 2], max_new_tokens=3)
    rep = _replica(_FakeEngine(), q)
    for _ in range(4):
        rep._iterate()
    done = q.result(uid, timeout=1.0)
    assert done.trace_id
    names = {s["name"] for s in tracing.spans()
             if s.get("trace_id") == done.trace_id}
    assert {"request.submit", "request.queue_wait", "request.prefill",
            "request.decode_block", "request.serve",
            "request.response"} <= names
    st = slo.state()
    assert st["requests_scored"] == 1
    (ex,) = st["slow_request_exemplars"]
    assert ex["trace_id"] == done.trace_id
    assert set(ex["phases_ms"]) == {"queue_wait", "prefill", "decode"}


def test_rejected_request_is_an_availability_bad_event(monkeypatch):
    from test_serve import _FakeEngine, _replica

    slo = _slo_tracker(monkeypatch, window=16)
    monkeypatch.setattr(tracing, "_slo", slo)
    q = RequestQueue()
    uid = q.submit(list(range(100)), max_new_tokens=4)  # > max_seq=64
    rep = _replica(_FakeEngine(), q)
    rep._iterate()
    assert q.result(uid, timeout=1.0).finish == "rejected"
    st = slo.state()["slo"]
    assert st["availability"]["bad_total"] == 1
    assert st["latency"]["window_observed"] == 0


# --------------------------------------------------- 2-rank merged trace

def test_one_trace_id_spans_both_ranks_in_merged_trace(tmp_path,
                                                       monkeypatch):
    """The acceptance shape of the tentpole, fast-tier: a frontend (this
    process, rank 0) submits ONE traced request to a real replica worker
    process (rank 1, ``python -m horovod_tpu.serve``); both dump profile
    snapshots into one dir; the merged Perfetto trace must show that
    trace_id's spans on BOTH ranks' request lanes, joined by a flow."""
    from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer
    from horovod_tpu.serve.queue import KVQueueFrontend

    server = RendezvousServer(host="127.0.0.1")
    port = server.start()
    proc = None
    try:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "HOROVOD_RANK": "1",
            "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
            "HOROVOD_RENDEZVOUS_HTTP_PORT": str(port),
            "HOROVOD_PROFILE_DIR": str(tmp_path),
            "HOROVOD_SERVE_ADMISSION_MS": "1",
            "JAX_PLATFORMS": "cpu",
        })
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.serve", "--vocab", "64",
             "--d-model", "16", "--layers", "1", "--heads", "1",
             "--d-ff", "32", "--max-seq", "32"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

        front = KVQueueFrontend(
            KVStoreClient("127.0.0.1", port, scope="serve", timeout=10.0))
        assert front.wait_for_replicas(1, timeout=90.0) == [1]
        req = Request(uid="traced-1", prompt=[1, 2, 3, 4],
                      max_new_tokens=4, submitted_s=time.monotonic())
        front.submit(req, rank=1)
        trace_id = req.trace_id
        assert trace_id
        deadline = time.monotonic() + 90.0
        while front.pending() and time.monotonic() < deadline:
            front.poll_responses()
            time.sleep(0.05)
        assert front.pending() == 0, "traced request never completed"
        assert front._done["traced-1"].trace_id == trace_id
        front.stop_fleet()
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, out[-2000:]

        # the worker's finalize dumped profile-rank-1.json; dump the
        # frontend's spans (this process) alongside it and merge
        profiler.dump(path=str(tmp_path / "profile-rank-0.json"),
                      ship=False)
        merged_path, _ = profiler.merge_profile_dir(str(tmp_path))
        with open(merged_path) as f:
            merged = json.load(f)["traceEvents"]
        ours = [e for e in merged if e.get("ph") == "X"
                and e.get("cat") == "request"
                and (e.get("args") or {}).get("trace_id") == trace_id]
        assert {e["args"]["rank"] for e in ours} == {0, 1}
        assert len({e["pid"] for e in ours}) >= 2   # two request lanes
        names = {e["name"] for e in ours}
        assert "request.submit" in names            # frontend side
        assert "request.serve" in names             # replica side
        flows = [e for e in merged if e.get("ph") in ("s", "t", "f")
                 and e.get("id") == trace_id]
        assert [f for f in flows if f["ph"] == "s"] and \
            [f for f in flows if f["ph"] == "f"]
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        server.stop()
