"""ZeRO-1 sharded optimizer states (parallel/zero.py).

The load-bearing claims, each tested here:

- allreduce == reducescatter + allgather, bit for bit, per reduce op and
  dtype (the identity the sharded data plane is built on);
- ``sharded_update(optax.sgd)`` is BIT-IDENTICAL to the replicated
  ``DistributedOptimizer`` path (elementwise inner transform);
- ``sharded_adamw`` tracks replicated optax.adamw within f32 round-off
  while holding ~1/N of the optimizer-state bytes per chip;
- steady state builds ZERO new programs after warmup (the PR-3
  invariant extended to the sharded path);
- invalid configurations fail loudly, not wrongly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def _metric(hvd, name, default=0):
    m = hvd.metrics().get(name)
    if not m or not m.get("values"):
        return default
    return m["values"][0]["value"]


def _uneven_tree(rng, dtype=np.float32, integer=False):
    """Leaf sizes deliberately indivisible by world=8 (3, 5, 70, 11)."""

    def draw(shape):
        if integer:
            return np.asarray(rng.randint(-50, 50, size=shape), dtype)
        return np.asarray(rng.randn(*shape), dtype)

    return {
        "a": jnp.asarray(draw((3,))),
        "b": jnp.asarray(draw((5, 14))),
        "c": {"w": jnp.asarray(draw((11,)))},
    }


class TestRoundTripIdentity:
    """Satellite: eager reducescatter -> allgather must reproduce the
    allreduce result bit for bit — sum and avg, f32/bf16/i32, with a
    leaf size that needs padding to divide by world."""

    @pytest.mark.parametrize("average", [False, True])
    @pytest.mark.parametrize("np_dtype", ["float32", "bfloat16", "int32"])
    def test_stacked_round_trip_matches_allreduce(self, hvd, average,
                                                  np_dtype):
        if average and np_dtype == "int32":
            pytest.skip("average over int32 is not closed in-dtype")
        w = hvd.size()
        rng = np.random.RandomState(3)
        dt = jnp.dtype(np_dtype)
        # 3 elems/worker after padding 17 -> 24 (uneven leaf size)
        n = 17
        pad = -n % w
        vals = [np.round(rng.randn(n) * 4).astype("float32")
                for _ in range(w)]
        padded = [jnp.asarray(np.concatenate([v, np.zeros(pad, "float32")])
                              ).astype(dt) for v in vals]

        ar = hvd.allreduce(hvd.stack_per_worker(padded), average=average)
        # (w, per) per-worker shards -> gathered back to the full vector
        shards = hvd.reducescatter(hvd.stack_per_worker(padded),
                                   average=average)
        rt = hvd.allgather(shards)

        np.testing.assert_array_equal(
            np.asarray(rt.astype(jnp.float32)),
            np.asarray(ar.astype(jnp.float32)),
            err_msg=f"round-trip != allreduce "
                    f"({np_dtype}, average={average})")

    def test_round_trip_flat_mesh(self, hvd_flat):
        w = hvd_flat.size()
        vals = [np.arange(w * 3, dtype="float32") * (i + 1)
                for i in range(w)]
        ar = hvd_flat.allreduce(hvd_flat.stack_per_worker(vals),
                                average=True)
        rt = hvd_flat.allgather(hvd_flat.reducescatter(
            hvd_flat.stack_per_worker(vals), average=True))
        np.testing.assert_array_equal(np.asarray(rt), np.asarray(ar))


class TestShardedSGDParity:
    def test_replicated_mode_bit_parity(self, hvd):
        """Plain (replicated) eager arrays: sharded plain SGD must
        produce the SAME BITS as the replicated DistributedOptimizer
        path. (Momentum SGD is covered by the allclose test below: XLA
        may contract its multiply-add to an FMA differently on the flat
        buffer than on per-leaf shapes — a 1-ulp layout artifact, not a
        data-plane difference.)"""
        rng = np.random.RandomState(0)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(1))

        rep = hvd.DistributedOptimizer(optax.sgd(0.05))
        rep_state = rep.init(params)
        sh = hvd.sharded_update(optax.sgd(0.05))
        sh_state = sh.init(params)

        p_rep, p_sh = params, params
        for _ in range(3):
            upd, rep_state = rep.update(grads, rep_state, p_rep)
            p_rep = optax.apply_updates(p_rep, upd)
            upd, sh_state = sh.update(grads, sh_state, p_sh)
            p_sh = optax.apply_updates(p_sh, upd)
        for k in ("a", "b"):
            np.testing.assert_array_equal(
                np.asarray(p_sh[k]), np.asarray(p_rep[k]),
                err_msg=f"sharded SGD diverged bitwise on leaf {k}")
        np.testing.assert_array_equal(np.asarray(p_sh["c"]["w"]),
                                      np.asarray(p_rep["c"]["w"]))

    def test_momentum_sgd_allclose(self, hvd):
        """Momentum SGD: allclose at f32 round-off (see bit-parity note
        above) over several steps."""
        rng = np.random.RandomState(11)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(12))
        rep = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9))
        rep_state = rep.init(params)
        sh = hvd.sharded_update(optax.sgd(0.05, momentum=0.9))
        sh_state = sh.init(params)
        p_rep, p_sh = params, params
        for _ in range(3):
            upd, rep_state = rep.update(grads, rep_state, p_rep)
            p_rep = optax.apply_updates(p_rep, upd)
            upd, sh_state = sh.update(grads, sh_state, p_sh)
            p_sh = optax.apply_updates(p_sh, upd)
        for k in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(p_sh[k]), np.asarray(p_rep[k]),
                rtol=1e-6, atol=1e-6)

    def test_stacked_mode_matches_mean_grad(self, hvd):
        """Per-worker stacked grads: the sharded update must equal SGD on
        the mean gradient, bit for bit."""
        w = hvd.size()
        rng = np.random.RandomState(2)
        params = {"w": jnp.asarray(rng.randn(13).astype(np.float32))}
        per_worker = [rng.randn(13).astype(np.float32) for _ in range(w)]
        stacked = {"w": hvd.stack_per_worker(
            [jnp.asarray(g) for g in per_worker])}

        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        upd, state = sh.update(stacked, state, params)
        p_new = optax.apply_updates(params, upd)

        mean_g = jnp.mean(jnp.stack([jnp.asarray(g) for g in per_worker]),
                          axis=0)
        expect = np.asarray(params["w"] - 0.1 * mean_g)
        np.testing.assert_array_equal(np.asarray(p_new["w"]), expect)

    def test_zero_steady_state_program_builds(self, hvd):
        """After the first update (warmup), further updates must build
        zero new programs — the PR-3 compile invariant."""
        rng = np.random.RandomState(4)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(5))
        sh = hvd.sharded_update(optax.sgd(0.01))
        state = sh.init(params)
        upd, state = sh.update(grads, state, params)  # warmup
        builds0 = _metric(hvd, "horovod_sharded_program_builds_total")
        for _ in range(3):
            upd, state = sh.update(grads, state, params)
        assert _metric(hvd, "horovod_sharded_program_builds_total") \
            == builds0, "steady-state sharded update built a new program"

    def test_state_bytes_gauge_reports_shard(self, hvd):
        """horovod_sharded_state_bytes must report ~1/N of the replicated
        optimizer-state footprint (padding makes it >=, never >2x)."""
        w = hvd.size()
        rng = np.random.RandomState(6)
        params = {"w": jnp.asarray(rng.randn(4096).astype(np.float32))}
        sh = hvd.sharded_update(optax.sgd(0.01, momentum=0.9))
        state = sh.init(params)
        upd, state = sh.update(params, state, params)
        got = _metric(hvd, "horovod_sharded_state_bytes")
        replicated = 4096 * 4  # sgd momentum: one f32 slot per param
        assert got < replicated, got
        assert got >= replicated // w, got


class TestShardedAdamW:
    def test_matches_replicated_optax(self, hvd):
        """Fused flat-buffer AdamW vs replicated optax.adamw: allclose at
        f32 round-off over several steps, uneven leaf sizes."""
        rng = np.random.RandomState(0)
        params = _uneven_tree(rng)
        ref = optax.adamw(1e-2, weight_decay=1e-3)
        ref_state = ref.init(params)
        sh = hvd.sharded_adamw(1e-2, weight_decay=1e-3)
        state = sh.init(params)

        p_ref, p_sh = params, params
        for i in range(4):
            grads = _uneven_tree(np.random.RandomState(10 + i))
            upd, ref_state = ref.update(grads, ref_state, p_ref)
            p_ref = optax.apply_updates(p_ref, upd)
            p_sh, state = sh.apply(p_sh, state, grads)
            for path in (("a",), ("b",), ("c", "w")):
                a, b = p_sh, p_ref
                for k in path:
                    a, b = a[k], b[k]
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6,
                    err_msg=f"step {i} leaf {path}")

    def test_bf16_params_keep_f32_master(self, hvd):
        """bf16 params: the master copy accumulates in f32, so many tiny
        steps must not be lost to bf16 round-off (the motivating case
        for master weights)."""
        params = {"w": jnp.ones((257,), jnp.bfloat16)}
        sh = hvd.sharded_adamw(1e-4, weight_decay=0.0)
        state = sh.init(params)
        p = params
        for i in range(3):
            g = {"w": jnp.full((257,), 0.5, jnp.bfloat16)}
            p, state = sh.apply(p, state, g)
        assert p["w"].dtype == jnp.bfloat16
        # master shards stay f32 and accumulate the sub-bf16-ulp steps
        # (3 x ~1e-4 is below bf16 resolution at 1.0 — the cast params
        # may legitimately still read 1.0; the master must not)
        assert len(state.master) == 1
        m = state.master[0]
        assert m.dtype == jnp.float32
        real = jnp.reshape(m, (-1,))[:257]  # tail is reduction-id pad
        moved = float(jnp.max(jnp.abs(real - 1.0)))
        assert 1e-5 < moved < 1e-2, moved

    def test_zero_steady_state_builds(self, hvd):
        rng = np.random.RandomState(7)
        params = _uneven_tree(rng)
        sh = hvd.sharded_adamw(1e-3)
        state = sh.init(params)
        p, state = sh.apply(params, state, params)  # warmup
        builds0 = _metric(hvd, "horovod_sharded_program_builds_total")
        for _ in range(3):
            p, state = sh.apply(p, state, params)
        assert _metric(hvd, "horovod_sharded_program_builds_total") \
            == builds0


class TestTracerMode:
    def test_sharded_sgd_under_shard_map(self, hvd):
        """Tracer mode: psum_scatter/all_gather inside shard_map must
        match the replicated result."""
        mesh = hvd.mesh()
        rng = np.random.RandomState(8)
        params = {"w": jnp.asarray(rng.randn(24).astype(np.float32))}
        per_dev = rng.randn(8, 24).astype(np.float32)
        sh = hvd.sharded_update(optax.sgd(0.1))

        def step(g):
            state = sh.init(params)
            upd, _ = sh.update({"w": g}, state, params)
            return optax.apply_updates(params, upd)

        out = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
            out_specs=P(), check_vma=False))(
                jnp.asarray(per_dev.reshape(-1)))
        expect = np.asarray(params["w"]) - 0.1 * per_dev.mean(0)
        np.testing.assert_allclose(np.asarray(out["w"]), expect,
                                   rtol=1e-6, atol=1e-6)


class TestErrors:
    def test_backward_passes_per_step_rejected(self, hvd):
        with pytest.raises(ValueError, match="backward_passes_per_step"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     backward_passes_per_step=2,
                                     shard_optimizer_states=True)

    def test_distributed_optimizer_sharding_flag(self, hvd):
        """shard_optimizer_states=True returns the ZeRO-1 wrapper and
        trains identically to plain sharded_update."""
        rng = np.random.RandomState(9)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(10))
        opt = hvd.DistributedOptimizer(optax.sgd(0.5),
                                       shard_optimizer_states=True)
        state = opt.init(params)
        assert isinstance(state, hvd.ShardedOptState)
        upd, state = opt.update(grads, state, params)
        p = optax.apply_updates(params, upd)
        np.testing.assert_array_equal(
            np.asarray(p["a"]),
            np.asarray(params["a"] - 0.5 * grads["a"]))

    def test_mixed_stacked_and_plain_leaves_rejected(self, hvd):
        w = hvd.size()
        params = {"a": jnp.ones((4,)), "b": jnp.ones((6,))}
        grads = {
            "a": hvd.stack_per_worker([jnp.ones((4,))] * w),
            "b": jnp.ones((6,)),  # plain replicated leaf
        }
        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        with pytest.raises(ValueError):
            sh.update(grads, state, params)

    def test_leaf_count_mismatch_rejected(self, hvd):
        params = {"a": jnp.ones((4,)), "b": jnp.ones((6,))}
        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        with pytest.raises((ValueError, TypeError)):
            sh.update({"a": jnp.ones((4,))}, state,
                      {"a": jnp.ones((4,))})


class TestStage2ShardedGrads:
    """ZeRO-2: gradients live only as the local 1/N shard — scattered
    directly (``scatter_gradients``) or released bucket-by-bucket as
    reduce-scatters (``GradReleasePlan(reduce_scatter=True)``), then
    consumed by the partition-aligned sharded optimizer without ever
    reassembling the full gradient."""

    def test_scatter_then_apply_matches_full_grads_bitwise(self, hvd):
        rng = np.random.RandomState(20)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(21))
        opt = hvd.sharded_adamw(1e-2)
        s_full = opt.init(params)
        s_pre = opt.init(params)
        p_full, _ = opt.apply(params, s_full, grads)
        sg = hvd.scatter_gradients(grads, spec=s_pre.spec)
        assert isinstance(sg, hvd.ShardedGrads)
        p_pre, _ = opt.apply(params, s_pre, sg)
        for a, b in zip(jax.tree_util.tree_leaves(p_full),
                        jax.tree_util.tree_leaves(p_pre)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_plan_reduce_scatter_release_matches_allreduce_plan(self, hvd):
        """Bit parity pin: a reduce-scatter-release plan feeding the
        partition-aligned AdamW must match the allreduce-release plan
        feeding the same layout, over multiple eager steps."""
        from horovod_tpu.parallel import buckets as buckets_mod

        rng = np.random.RandomState(22)
        params = _uneven_tree(rng)
        plan_rs = buckets_mod.GradReleasePlan(reduce_scatter=True,
                                              bucket_bytes=256)
        plan_ar = buckets_mod.GradReleasePlan(bucket_bytes=256)
        part = plan_rs.zero_partition(params)
        assert part == plan_ar.zero_partition(params)
        opt = hvd.sharded_adamw(1e-2, partition=part)
        s_rs, s_ar = opt.init(params), opt.init(params)

        def make_loss(plan):
            def loss(p):
                t = plan.tag(p)
                return (jnp.sum(t["a"] ** 2) + jnp.sum(t["b"] ** 2)
                        + jnp.sum(t["c"]["w"] ** 2)) / 2.0
            return loss

        p_rs, p_ar = params, params
        for step in range(3):
            g = jax.grad(make_loss(plan_rs))(p_rs)
            sg = plan_rs.gather(g)
            assert isinstance(sg, hvd.ShardedGrads), type(sg)
            p_rs, s_rs = opt.apply(p_rs, s_rs, sg)
            g = jax.grad(make_loss(plan_ar))(p_ar)
            p_ar, s_ar = opt.apply(p_ar, s_ar, plan_ar.gather(g))
            for a, b in zip(jax.tree_util.tree_leaves(p_rs),
                            jax.tree_util.tree_leaves(p_ar)):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"stage-2 release diverged at step {step}")

    def test_grad_wire_bus_bytes_halved(self, hvd):
        """ISSUE 20 acceptance: stage-2 gradient wire cost on the comms
        ledger is exactly half the replicated allreduce baseline — same
        payload bytes per bucket, bus factor (N-1)/N vs 2(N-1)/N."""
        from horovod_tpu import comms
        from horovod_tpu.parallel import buckets as buckets_mod

        w = hvd.size()
        # world-divisible leaf sizes: RS padding == allreduce payload
        params = {"a": jnp.ones((16 * w,), jnp.float32),
                  "b": jnp.ones((4 * w, 4), jnp.float32)}

        def run(plan, op):
            key = (op, "bucket_wire")
            t = comms.tracker()
            before = t._totals.get(key, [0, 0, 0.0])[0]

            def loss(p):
                t_ = plan.tag(p)
                return (jnp.sum(t_["a"] ** 2) + jnp.sum(t_["b"] ** 2)) / 2.0

            plan.gather(jax.grad(loss)(params))
            return t._totals.get(key, [0, 0, 0.0])[0] - before

        ar_payload = run(buckets_mod.GradReleasePlan(bucket_bytes=128),
                         "allreduce")
        rs_payload = run(
            buckets_mod.GradReleasePlan(reduce_scatter=True,
                                        bucket_bytes=128),
            "reducescatter")
        assert ar_payload > 0 and rs_payload > 0
        assert rs_payload == ar_payload  # same wire payload...
        ar_bus = ar_payload * comms.bus_factor("allreduce", w)
        rs_bus = rs_payload * comms.bus_factor("reducescatter", w)
        # ...but half the bus bytes: the gather half never rides the wire
        assert rs_bus * 2 == ar_bus, (rs_bus, ar_bus)

    def test_allreduce_gradients_rejects_sharded_grads(self, hvd):
        rng = np.random.RandomState(23)
        params = _uneven_tree(rng)
        opt = hvd.sharded_adamw(1e-2)
        state = opt.init(params)
        sg = hvd.scatter_gradients(params, spec=state.spec)
        with pytest.raises(TypeError, match="already the reduced"):
            hvd.allreduce_gradients(sg)

    def test_partition_mismatch_actionable(self, hvd):
        """A plan-bucketed ShardedGrads fed to a default-layout optimizer
        must fail loudly, naming the partition= fix."""
        from horovod_tpu.parallel import buckets as buckets_mod

        rng = np.random.RandomState(24)
        params = _uneven_tree(rng)
        plan = buckets_mod.GradReleasePlan(reduce_scatter=True,
                                           bucket_bytes=64)
        plan.zero_partition(params)
        opt = hvd.sharded_adamw(1e-2)  # default dtype-sorted layout
        state = opt.init(params)

        def loss(p):
            t = plan.tag(p)
            return (jnp.sum(t["a"] ** 2) + jnp.sum(t["b"] ** 2)
                    + jnp.sum(t["c"]["w"] ** 2)) / 2.0

        sg = plan.gather(jax.grad(loss)(params))
        if sg.spec.groups == state.spec.groups:
            pytest.skip("layouts happen to coincide at this bucket size")
        with pytest.raises(ValueError, match="zero_partition"):
            opt.apply(params, state, sg)

    def test_sharded_update_consumes_shards(self, hvd):
        rng = np.random.RandomState(25)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(26))
        sh = hvd.sharded_update(optax.sgd(0.5))
        s_full, s_pre = sh.init(params), sh.init(params)
        upd_full, _ = sh.update(grads, s_full, params)
        sg = hvd.scatter_gradients(grads, spec=s_pre.spec)
        upd_pre, _ = sh.update(sg, s_pre, params)
        for a, b in zip(jax.tree_util.tree_leaves(upd_full),
                        jax.tree_util.tree_leaves(upd_pre)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grad_shards_in_memory_ledger(self, hvd):
        from horovod_tpu import memory

        rng = np.random.RandomState(27)
        params = _uneven_tree(rng)
        opt = hvd.sharded_adamw(1e-2)
        state = opt.init(params)
        hvd.scatter_gradients(params, spec=state.spec)
        ledger = memory.tracker().ledger()
        assert "grad_shards" in ledger["subsystems"]
        got = ledger["subsystems"]["grad_shards"]["bytes"]
        full = sum(x.nbytes for x in jax.tree_util.tree_leaves(params))
        assert 0 < got < full, (got, full)
        assert "grad_shards" in memory.DEVICE_SUBSYSTEMS


class TestStage3ShardedParams:
    """ZeRO-3: params sharded at rest, gathered on demand bucket-by-
    bucket under the prefetch window; the update consumes gradient
    shards and returns new parameter shards without materializing the
    full tree."""

    def test_shard_gather_round_trip_bitwise(self, hvd):
        rng = np.random.RandomState(30)
        params = _uneven_tree(rng)
        sp = hvd.shard_params(params)
        assert isinstance(sp, hvd.ShardedParams)
        full = hvd.gather_params(sp)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(full)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stage3_training_matches_stage1_bitwise(self, hvd):
        """Bit parity pin: N steps over sharded-at-rest params equal the
        same N steps over replicated params, elementwise AdamW."""
        rng = np.random.RandomState(31)
        params = _uneven_tree(rng)
        opt = hvd.sharded_adamw(1e-2, weight_decay=1e-3)
        s1 = opt.init(params)
        sp = hvd.shard_params(params)
        s3 = opt.init(sp)
        p1 = params
        for i in range(3):
            grads = _uneven_tree(np.random.RandomState(40 + i))
            p1, s1 = opt.apply(p1, s1, grads)
            sg = hvd.scatter_gradients(grads, spec=s3.spec)
            sp, s3 = opt.apply(sp, s3, sg)
            assert isinstance(sp, hvd.ShardedParams)
        full = hvd.gather_params(sp)
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(full)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg="stage-3 training diverged from replicated")

    def test_iter_param_buckets_covers_all_leaves(self, hvd):
        rng = np.random.RandomState(32)
        params = _uneven_tree(rng)
        sp = hvd.shard_params(params, partition=[[0], [1], [2]])
        assert len(sp.spec.groups) == 3
        seen = {}
        for gi, leafmap in hvd.iter_param_buckets(sp, prefetch=2):
            seen.update(leafmap)
        leaves = jax.tree_util.tree_leaves(params)
        assert sorted(seen) == list(range(len(leaves)))
        for i, leaf in enumerate(leaves):
            np.testing.assert_array_equal(np.asarray(seen[i]),
                                          np.asarray(leaf))

    def test_prefetch_hides_comm(self, hvd):
        """With a >1 window, later buckets' allgathers dispatch under
        earlier buckets' consumption — the hidden-seconds counter must
        advance and the fraction stay in [0, 1]."""
        rng = np.random.RandomState(33)
        params = {f"w{i}": jnp.asarray(rng.randn(512).astype(np.float32))
                  for i in range(4)}
        sp = hvd.shard_params(params, partition=[[0], [1], [2], [3]])
        hidden0 = _metric(hvd, "horovod_zero_gather_hidden_seconds_total",
                          0.0)
        for _gi, _bucket in hvd.iter_param_buckets(sp, prefetch=3):
            pass
        hidden1 = _metric(hvd, "horovod_zero_gather_hidden_seconds_total",
                          0.0)
        assert hidden1 > hidden0, "no comm was hidden under the window"
        from horovod_tpu.parallel import zero

        assert 0.0 < zero.gather_hidden_fraction() <= 1.0

    def test_gather_stall_charged_to_exposed_comm(self, hvd):
        """Goodput attribution: blocked gather waits land in
        ``exposed_comm``, not ``input_idle``."""
        from horovod_tpu import goodput

        rng = np.random.RandomState(34)
        params = _uneven_tree(rng)
        sp = hvd.shard_params(params, partition=[[0], [1], [2]])
        t = goodput.tracker()
        assert t.enabled
        before = t._cat.get("exposed_comm", 0.0)
        # window 1 = no lookahead: every wait is a blocked stall
        for _gi, _bucket in hvd.iter_param_buckets(sp, prefetch=1):
            pass
        assert t._cat.get("exposed_comm", 0.0) > before

    def test_zero_steady_state_builds_stages_2_and_3(self, hvd):
        rng = np.random.RandomState(35)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(36))
        opt = hvd.sharded_adamw(1e-3)
        sp = hvd.shard_params(params)
        state = opt.init(sp)
        # warmup compiles scatter, apply, and gather programs
        sg = hvd.scatter_gradients(grads, spec=state.spec)
        sp, state = opt.apply(sp, state, sg)
        hvd.gather_params(sp)
        builds0 = _metric(hvd, "horovod_sharded_program_builds_total")
        for _ in range(3):
            sg = hvd.scatter_gradients(grads, spec=state.spec)
            sp, state = opt.apply(sp, state, sg)
            hvd.gather_params(sp)
        assert _metric(hvd, "horovod_sharded_program_builds_total") \
            == builds0, "steady-state stage-2/3 step built a new program"

    def test_prefetch_knob_and_autotune_override(self, hvd, monkeypatch):
        from horovod_tpu.parallel import zero

        monkeypatch.delenv("HOROVOD_ZERO_PREFETCH_BUCKETS", raising=False)
        zero.set_autotuned_prefetch_buckets(0)
        assert zero.prefetch_buckets_from_env() \
            == zero.DEFAULT_ZERO_PREFETCH_BUCKETS
        monkeypatch.setenv("HOROVOD_ZERO_PREFETCH_BUCKETS", "5")
        assert zero.prefetch_buckets_from_env() == 5
        # a committed autotune value wins over the static env knob
        zero.set_autotuned_prefetch_buckets(3)
        try:
            assert zero.prefetch_buckets_from_env() == 3
        finally:
            zero.set_autotuned_prefetch_buckets(0)

    def test_stage_from_env(self, hvd, monkeypatch):
        from horovod_tpu.parallel import zero

        monkeypatch.delenv("HOROVOD_ZERO_STAGE", raising=False)
        assert zero.stage_from_env() == 1
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", "3")
        assert zero.stage_from_env() == 3
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", "7")
        assert zero.stage_from_env() == 3  # clamped

    def test_training_auto_plan_follows_stage(self, hvd, monkeypatch):
        from horovod_tpu import training
        from horovod_tpu.parallel import buckets as buckets_mod

        monkeypatch.setenv("HOROVOD_GRAD_BUCKET_RELEASE", "1")
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", "2")
        plan = training._resolve_grad_release(None)
        assert isinstance(plan, buckets_mod.GradReleasePlan)
        assert plan.reduce_scatter
        monkeypatch.setenv("HOROVOD_ZERO_STAGE", "1")
        assert not training._resolve_grad_release(None).reduce_scatter

    def test_oom_sized_replicated_trains_at_stage3(self, hvd):
        """ISSUE 20 acceptance (CPU-sim memory ledger): a model whose
        replicated footprint (params + grads + fp32 master/moments) would
        not fit a synthetic per-chip budget trains at stage 3 with every
        resident subsystem shard-sized, and reaches the right weights."""
        from horovod_tpu import memory

        w = hvd.size()
        n = 8192  # f32 elems; replicated step needs ~5 copies of this
        params = {"w": jnp.ones((n,), jnp.float32)}
        full = n * 4
        # replicated: params + grads + master + mu + nu, all full-size
        replicated_need = 5 * full
        budget = 2 * full  # fits shards (5*full/w + activations), not 5x
        assert replicated_need > budget
        sp = hvd.shard_params(params)
        opt = hvd.sharded_adamw(0.1, b1=0.0, b2=0.0, eps=0.0,
                                weight_decay=0.0)
        state = opt.init(sp)
        for _ in range(2):
            # grads computed bucket-wise: the full tree never materializes
            gshards = []
            for gi, bucket in hvd.iter_param_buckets(sp):
                g = sp.spec.groups[gi]
                vals = {li: jnp.ones_like(bucket[li]) for li in g.indices}
                gshards.append(vals)
            flat_grads = {}
            for vals in gshards:
                for li, v in vals.items():
                    flat_grads[li] = v
            sg = hvd.scatter_gradients(
                jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(params),
                    [flat_grads[i] for i in sorted(flat_grads)]),
                spec=state.spec)
            sp, state = opt.apply(sp, state, sg)
        ledger = memory.tracker().ledger()
        subs = ledger["subsystems"]
        resident = (subs.get("param_shards", {}).get("bytes", 0)
                    + subs.get("grad_shards", {}).get("bytes", 0)
                    + subs.get("optimizer_shards", {}).get("bytes", 0))
        assert 0 < resident <= budget, (resident, budget)
        # per-subsystem shards actually shrank toward 1/N
        assert subs["param_shards"]["bytes"] <= full // w * 2
        # grad=1 every step: m_hat=1, v_hat=1, eps=0 -> each update is
        # exactly -lr
        full_p = hvd.gather_params(sp)
        np.testing.assert_allclose(np.asarray(full_p["w"]),
                                   np.ones(n) * (1.0 - 0.1 * 2),
                                   rtol=1e-6)
