"""ZeRO-1 sharded optimizer states (parallel/zero.py).

The load-bearing claims, each tested here:

- allreduce == reducescatter + allgather, bit for bit, per reduce op and
  dtype (the identity the sharded data plane is built on);
- ``sharded_update(optax.sgd)`` is BIT-IDENTICAL to the replicated
  ``DistributedOptimizer`` path (elementwise inner transform);
- ``sharded_adamw`` tracks replicated optax.adamw within f32 round-off
  while holding ~1/N of the optimizer-state bytes per chip;
- steady state builds ZERO new programs after warmup (the PR-3
  invariant extended to the sharded path);
- invalid configurations fail loudly, not wrongly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P


def _metric(hvd, name, default=0):
    m = hvd.metrics().get(name)
    if not m or not m.get("values"):
        return default
    return m["values"][0]["value"]


def _uneven_tree(rng, dtype=np.float32, integer=False):
    """Leaf sizes deliberately indivisible by world=8 (3, 5, 70, 11)."""

    def draw(shape):
        if integer:
            return np.asarray(rng.randint(-50, 50, size=shape), dtype)
        return np.asarray(rng.randn(*shape), dtype)

    return {
        "a": jnp.asarray(draw((3,))),
        "b": jnp.asarray(draw((5, 14))),
        "c": {"w": jnp.asarray(draw((11,)))},
    }


class TestRoundTripIdentity:
    """Satellite: eager reducescatter -> allgather must reproduce the
    allreduce result bit for bit — sum and avg, f32/bf16/i32, with a
    leaf size that needs padding to divide by world."""

    @pytest.mark.parametrize("average", [False, True])
    @pytest.mark.parametrize("np_dtype", ["float32", "bfloat16", "int32"])
    def test_stacked_round_trip_matches_allreduce(self, hvd, average,
                                                  np_dtype):
        if average and np_dtype == "int32":
            pytest.skip("average over int32 is not closed in-dtype")
        w = hvd.size()
        rng = np.random.RandomState(3)
        dt = jnp.dtype(np_dtype)
        # 3 elems/worker after padding 17 -> 24 (uneven leaf size)
        n = 17
        pad = -n % w
        vals = [np.round(rng.randn(n) * 4).astype("float32")
                for _ in range(w)]
        padded = [jnp.asarray(np.concatenate([v, np.zeros(pad, "float32")])
                              ).astype(dt) for v in vals]

        ar = hvd.allreduce(hvd.stack_per_worker(padded), average=average)
        # (w, per) per-worker shards -> gathered back to the full vector
        shards = hvd.reducescatter(hvd.stack_per_worker(padded),
                                   average=average)
        rt = hvd.allgather(shards)

        np.testing.assert_array_equal(
            np.asarray(rt.astype(jnp.float32)),
            np.asarray(ar.astype(jnp.float32)),
            err_msg=f"round-trip != allreduce "
                    f"({np_dtype}, average={average})")

    def test_round_trip_flat_mesh(self, hvd_flat):
        w = hvd_flat.size()
        vals = [np.arange(w * 3, dtype="float32") * (i + 1)
                for i in range(w)]
        ar = hvd_flat.allreduce(hvd_flat.stack_per_worker(vals),
                                average=True)
        rt = hvd_flat.allgather(hvd_flat.reducescatter(
            hvd_flat.stack_per_worker(vals), average=True))
        np.testing.assert_array_equal(np.asarray(rt), np.asarray(ar))


class TestShardedSGDParity:
    def test_replicated_mode_bit_parity(self, hvd):
        """Plain (replicated) eager arrays: sharded plain SGD must
        produce the SAME BITS as the replicated DistributedOptimizer
        path. (Momentum SGD is covered by the allclose test below: XLA
        may contract its multiply-add to an FMA differently on the flat
        buffer than on per-leaf shapes — a 1-ulp layout artifact, not a
        data-plane difference.)"""
        rng = np.random.RandomState(0)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(1))

        rep = hvd.DistributedOptimizer(optax.sgd(0.05))
        rep_state = rep.init(params)
        sh = hvd.sharded_update(optax.sgd(0.05))
        sh_state = sh.init(params)

        p_rep, p_sh = params, params
        for _ in range(3):
            upd, rep_state = rep.update(grads, rep_state, p_rep)
            p_rep = optax.apply_updates(p_rep, upd)
            upd, sh_state = sh.update(grads, sh_state, p_sh)
            p_sh = optax.apply_updates(p_sh, upd)
        for k in ("a", "b"):
            np.testing.assert_array_equal(
                np.asarray(p_sh[k]), np.asarray(p_rep[k]),
                err_msg=f"sharded SGD diverged bitwise on leaf {k}")
        np.testing.assert_array_equal(np.asarray(p_sh["c"]["w"]),
                                      np.asarray(p_rep["c"]["w"]))

    def test_momentum_sgd_allclose(self, hvd):
        """Momentum SGD: allclose at f32 round-off (see bit-parity note
        above) over several steps."""
        rng = np.random.RandomState(11)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(12))
        rep = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9))
        rep_state = rep.init(params)
        sh = hvd.sharded_update(optax.sgd(0.05, momentum=0.9))
        sh_state = sh.init(params)
        p_rep, p_sh = params, params
        for _ in range(3):
            upd, rep_state = rep.update(grads, rep_state, p_rep)
            p_rep = optax.apply_updates(p_rep, upd)
            upd, sh_state = sh.update(grads, sh_state, p_sh)
            p_sh = optax.apply_updates(p_sh, upd)
        for k in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(p_sh[k]), np.asarray(p_rep[k]),
                rtol=1e-6, atol=1e-6)

    def test_stacked_mode_matches_mean_grad(self, hvd):
        """Per-worker stacked grads: the sharded update must equal SGD on
        the mean gradient, bit for bit."""
        w = hvd.size()
        rng = np.random.RandomState(2)
        params = {"w": jnp.asarray(rng.randn(13).astype(np.float32))}
        per_worker = [rng.randn(13).astype(np.float32) for _ in range(w)]
        stacked = {"w": hvd.stack_per_worker(
            [jnp.asarray(g) for g in per_worker])}

        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        upd, state = sh.update(stacked, state, params)
        p_new = optax.apply_updates(params, upd)

        mean_g = jnp.mean(jnp.stack([jnp.asarray(g) for g in per_worker]),
                          axis=0)
        expect = np.asarray(params["w"] - 0.1 * mean_g)
        np.testing.assert_array_equal(np.asarray(p_new["w"]), expect)

    def test_zero_steady_state_program_builds(self, hvd):
        """After the first update (warmup), further updates must build
        zero new programs — the PR-3 compile invariant."""
        rng = np.random.RandomState(4)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(5))
        sh = hvd.sharded_update(optax.sgd(0.01))
        state = sh.init(params)
        upd, state = sh.update(grads, state, params)  # warmup
        builds0 = _metric(hvd, "horovod_sharded_program_builds_total")
        for _ in range(3):
            upd, state = sh.update(grads, state, params)
        assert _metric(hvd, "horovod_sharded_program_builds_total") \
            == builds0, "steady-state sharded update built a new program"

    def test_state_bytes_gauge_reports_shard(self, hvd):
        """horovod_sharded_state_bytes must report ~1/N of the replicated
        optimizer-state footprint (padding makes it >=, never >2x)."""
        w = hvd.size()
        rng = np.random.RandomState(6)
        params = {"w": jnp.asarray(rng.randn(4096).astype(np.float32))}
        sh = hvd.sharded_update(optax.sgd(0.01, momentum=0.9))
        state = sh.init(params)
        upd, state = sh.update(params, state, params)
        got = _metric(hvd, "horovod_sharded_state_bytes")
        replicated = 4096 * 4  # sgd momentum: one f32 slot per param
        assert got < replicated, got
        assert got >= replicated // w, got


class TestShardedAdamW:
    def test_matches_replicated_optax(self, hvd):
        """Fused flat-buffer AdamW vs replicated optax.adamw: allclose at
        f32 round-off over several steps, uneven leaf sizes."""
        rng = np.random.RandomState(0)
        params = _uneven_tree(rng)
        ref = optax.adamw(1e-2, weight_decay=1e-3)
        ref_state = ref.init(params)
        sh = hvd.sharded_adamw(1e-2, weight_decay=1e-3)
        state = sh.init(params)

        p_ref, p_sh = params, params
        for i in range(4):
            grads = _uneven_tree(np.random.RandomState(10 + i))
            upd, ref_state = ref.update(grads, ref_state, p_ref)
            p_ref = optax.apply_updates(p_ref, upd)
            p_sh, state = sh.apply(p_sh, state, grads)
            for path in (("a",), ("b",), ("c", "w")):
                a, b = p_sh, p_ref
                for k in path:
                    a, b = a[k], b[k]
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6,
                    err_msg=f"step {i} leaf {path}")

    def test_bf16_params_keep_f32_master(self, hvd):
        """bf16 params: the master copy accumulates in f32, so many tiny
        steps must not be lost to bf16 round-off (the motivating case
        for master weights)."""
        params = {"w": jnp.ones((257,), jnp.bfloat16)}
        sh = hvd.sharded_adamw(1e-4, weight_decay=0.0)
        state = sh.init(params)
        p = params
        for i in range(3):
            g = {"w": jnp.full((257,), 0.5, jnp.bfloat16)}
            p, state = sh.apply(p, state, g)
        assert p["w"].dtype == jnp.bfloat16
        # master shards stay f32 and accumulate the sub-bf16-ulp steps
        # (3 x ~1e-4 is below bf16 resolution at 1.0 — the cast params
        # may legitimately still read 1.0; the master must not)
        assert len(state.master) == 1
        m = state.master[0]
        assert m.dtype == jnp.float32
        real = jnp.reshape(m, (-1,))[:257]  # tail is reduction-id pad
        moved = float(jnp.max(jnp.abs(real - 1.0)))
        assert 1e-5 < moved < 1e-2, moved

    def test_zero_steady_state_builds(self, hvd):
        rng = np.random.RandomState(7)
        params = _uneven_tree(rng)
        sh = hvd.sharded_adamw(1e-3)
        state = sh.init(params)
        p, state = sh.apply(params, state, params)  # warmup
        builds0 = _metric(hvd, "horovod_sharded_program_builds_total")
        for _ in range(3):
            p, state = sh.apply(p, state, params)
        assert _metric(hvd, "horovod_sharded_program_builds_total") \
            == builds0


class TestTracerMode:
    def test_sharded_sgd_under_shard_map(self, hvd):
        """Tracer mode: psum_scatter/all_gather inside shard_map must
        match the replicated result."""
        mesh = hvd.mesh()
        rng = np.random.RandomState(8)
        params = {"w": jnp.asarray(rng.randn(24).astype(np.float32))}
        per_dev = rng.randn(8, 24).astype(np.float32)
        sh = hvd.sharded_update(optax.sgd(0.1))

        def step(g):
            state = sh.init(params)
            upd, _ = sh.update({"w": g}, state, params)
            return optax.apply_updates(params, upd)

        out = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=P(hvd.GLOBAL_AXES),
            out_specs=P(), check_vma=False))(
                jnp.asarray(per_dev.reshape(-1)))
        expect = np.asarray(params["w"]) - 0.1 * per_dev.mean(0)
        np.testing.assert_allclose(np.asarray(out["w"]), expect,
                                   rtol=1e-6, atol=1e-6)


class TestErrors:
    def test_backward_passes_per_step_rejected(self, hvd):
        with pytest.raises(ValueError, match="backward_passes_per_step"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     backward_passes_per_step=2,
                                     shard_optimizer_states=True)

    def test_distributed_optimizer_sharding_flag(self, hvd):
        """shard_optimizer_states=True returns the ZeRO-1 wrapper and
        trains identically to plain sharded_update."""
        rng = np.random.RandomState(9)
        params = _uneven_tree(rng)
        grads = _uneven_tree(np.random.RandomState(10))
        opt = hvd.DistributedOptimizer(optax.sgd(0.5),
                                       shard_optimizer_states=True)
        state = opt.init(params)
        assert isinstance(state, hvd.ShardedOptState)
        upd, state = opt.update(grads, state, params)
        p = optax.apply_updates(params, upd)
        np.testing.assert_array_equal(
            np.asarray(p["a"]),
            np.asarray(params["a"] - 0.5 * grads["a"]))

    def test_mixed_stacked_and_plain_leaves_rejected(self, hvd):
        w = hvd.size()
        params = {"a": jnp.ones((4,)), "b": jnp.ones((6,))}
        grads = {
            "a": hvd.stack_per_worker([jnp.ones((4,))] * w),
            "b": jnp.ones((6,)),  # plain replicated leaf
        }
        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        with pytest.raises(ValueError):
            sh.update(grads, state, params)

    def test_leaf_count_mismatch_rejected(self, hvd):
        params = {"a": jnp.ones((4,)), "b": jnp.ones((6,))}
        sh = hvd.sharded_update(optax.sgd(0.1))
        state = sh.init(params)
        with pytest.raises((ValueError, TypeError)):
            sh.update({"a": jnp.ones((4,))}, state,
                      {"a": jnp.ones((4,))})
