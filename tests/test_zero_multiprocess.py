"""ZeRO-2 elastic fault-injection acceptance (ISSUE 20 satellite).

Fast-tier repeat of the chaos-matrix cell ``zero2_kill_mid_reducescatter``:
world=3 over the real socket/native transport, rank 1 hard-killed
*inside* a stage-2 bucket reduce-scatter (bucket 0's reduce-scatter
already in flight, later buckets never released). The survivors'
gather must fail the orphaned stage-2 tokens with WorkersDownError,
``@elastic.run`` re-forms them into a 2-worker generation,
``zero.resync`` rebuilds the sharded AdamW shards under the new world,
and training reaches the expected weights (w == step, every element)
with zero leaked fusion-buffer leases.
"""

import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.runtime.native import native_built

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "zero2_elastic_worker.py")

pytestmark = pytest.mark.skipif(
    not native_built(), reason="native transport not built")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(world, extra_env=None, timeout=240):
    rendezvous = RendezvousServer(host="127.0.0.1")
    http_port = rendezvous.start()
    socket_port = _free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "JAX_PLATFORMS": "cpu",
            })
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        rendezvous.stop()
    return procs, outs


def test_zero2_kill_mid_reducescatter_survivors_reshard():
    procs, outs = _launch(
        3, extra_env={
            "ZERO2_KILL_STEP": "3",
            "ZERO2_KILL_RANK": "1",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        })
    # the planted mid-reduce-scatter death exits with code 17
    assert procs[1].returncode == 17, outs[1]
    for i in (0, 2):
        assert procs[i].returncode == 0, (i, outs[i])
        assert "DONE" in outs[i], (i, outs[i])
        assert "step=6" in outs[i], (i, outs[i])
        assert "w=6" in outs[i], (i, outs[i])
        assert "size=2" in outs[i], (i, outs[i])
        # resync re-sharded the optimizer for the 2-worker generation
        assert "shard_world=2" in outs[i], (i, outs[i])
        # every failed stage-2 token returned its slab
        assert "leases_leaked=0" in outs[i], (i, outs[i])
        # the stage-2 wire was really exercised: 3 buckets per step
        released = int(outs[i].split("wire_released=")[1].split()[0])
        assert released >= 3 * 6, (i, outs[i])
