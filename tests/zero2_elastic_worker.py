"""Worker for the ZeRO-2 chaos cell ``zero2_kill_mid_reducescatter``
(ISSUE 20 satellite).

World=3 over the real socket/native transport. Every step runs a
bucketed eager backward through a ``GradReleasePlan(reduce_scatter=
True)`` — one leaf per bucket, so three reduce-scatters hit the wire
per step and the optimizer consumes the resulting ``zero.ShardedGrads``
directly (the full-gradient buffer is never reassembled). At
ZERO2_KILL_STEP the kill rank dies *mid-backward*: inside its second
bucket's reduce-scatter release, with bucket 0's reduce-scatter already
negotiated/in flight. The survivors' ``gather`` fails with
WorkersDownError on the orphaned tokens; ``@elastic.run`` re-forms them
into a 2-worker generation, ``zero.resync`` rebuilds the sharded AdamW
master/moment shards under the new world, and the SAME plan object
(zspec rebuilt for the new world) finishes the run. The final line
reports outstanding fusion-buffer leases — a failed token must return
its slab, so ``leases_leaked`` has to be 0.

Invariant: the loss is a plain sum so every averaged gradient element
is exactly 1; sharded AdamW with b1=b2=eps=weight_decay=0 and lr=-1
then adds exactly ``-lr * sign(g) == 1`` per element per step
regardless of world size — ``w == step`` at every commit, across the
re-form.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.parallel import buckets as buckets_mod

TOTAL_STEPS = int(os.environ.get("ELASTIC_TOTAL_STEPS", "6"))
KILL_STEP = int(os.environ.get("ZERO2_KILL_STEP", "3"))
KILL_RANK = int(os.environ.get("ZERO2_KILL_RANK", "1"))
ORIG_RANK = int(os.environ.get("HOROVOD_RANK", "0"))

PLAN = buckets_mod.GradReleasePlan(bucket_bytes=256,
                                   reduce_scatter=True)

_die_mid_rs = False
_real_release = buckets_mod.GradReleasePlan._release_reduce_scatter


def _release_and_maybe_die(self, bucket, values):
    _real_release(self, bucket, values)
    if _die_mid_rs and bucket.index >= 1:
        # bucket 0's reduce-scatter is already on the wire and later
        # buckets are still differentiating: abrupt death with stage-2
        # tokens genuinely in flight
        os._exit(17)


buckets_mod.GradReleasePlan._release_reduce_scatter = _release_and_maybe_die

OPT = None


def _params():
    # 384 B per leaf > bucket_bytes: one leaf per bucket, so three
    # reduce-scatters hit the wire per step and the kill lands with
    # bucket 0 genuinely in flight
    return {"a": jnp.zeros((96,), jnp.float32),
            "b": jnp.zeros((96,), jnp.float32),
            "c": jnp.zeros((96,), jnp.float32)}


def sharded_grads(params):
    def loss(p):
        return sum(x.sum() for x in
                   jax.tree_util.tree_leaves(PLAN.tag(p)))

    return PLAN.gather(jax.grad(loss)(params))


@elastic.run
def train(state):
    global _die_mid_rs
    while state.step < TOTAL_STEPS:
        _die_mid_rs = (ORIG_RANK == KILL_RANK
                       and state.step == KILL_STEP
                       and elastic.restarts() == 0)
        params = {k: jnp.asarray(v) for k, v in state.params.items()}
        sg = sharded_grads(params)
        _die_mid_rs = False
        params, state.optimizer = OPT.apply(params, state.optimizer, sg)
        state.params = {k: np.asarray(v) for k, v in params.items()}
        state.step += 1
        state.commit()
    return state


def main() -> int:
    global OPT
    from horovod_tpu.parallel import zero

    hvd.init()
    params = _params()
    # b1=b2=eps=weight_decay=0, lr=-1: the AdamW inner reduces to
    # -lr * sign(g) — grads of ones add exactly 1.0 per element per step
    OPT = hvd.sharded_adamw(-1.0, 0.0, 0.0, 0.0, 0.0,
                            partition=PLAN.zero_partition(params))
    # the sharded master is the source of truth: init it from the same
    # zeros the tracked params start at
    state = elastic.ArrayState(
        params={k: np.asarray(v) for k, v in params.items()},
        optimizer=OPT.init(params), step=0)
    train(state)

    from horovod_tpu.runtime.runtime import get_runtime

    mgr = get_runtime().executor.fusion_buffers
    with mgr._lock:
        free = sum(a.nbytes for lst in mgr._free.values() for a in lst)
    leaked = mgr.allocated_bytes() - free
    spec = state.optimizer.spec
    w_arr = np.concatenate([np.asarray(state.params[k]).reshape(-1)
                            for k in sorted(state.params)])
    w = float(w_arr[0])
    print(f"DONE rank={hvd.rank()} size={hvd.size()} step={state.step} "
          f"w={w:g} generation={elastic.restarts()} "
          f"wire_released={PLAN.wire_stats()['released']} "
          f"shard_world={spec.world} shard_rank={spec.rank} "
          f"leases_leaked={leaked}", flush=True)
    if state.step != TOTAL_STEPS:
        return 3
    # every element moved in lockstep across the re-form
    if not np.all(np.abs(w_arr - TOTAL_STEPS) < 1e-5):
        return 3
    # resync must have rebuilt the shards for the CURRENT world
    if spec.world != hvd.size() or spec.rank != hvd.rank():
        return 4
    if leaked != 0:
        return 5
    assert isinstance(state.optimizer, zero.FlatAdamState)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
