"""Worker script for the ZeRO-1 elastic acceptance test.

Launched by tests/test_elastic_multiprocess.py with world=3 and
``HOROVOD_FAULT_INJECT=kill:rank=1:step=3``: the optimizer state is
SHARDED (``hvd.sharded_update``), so a membership reform cannot just
re-broadcast rank 0's copy — ``ArrayState.sync`` must route the
sharded leaves through ``zero.resync`` (allgather surviving shards,
rebuild the flat buffer, slice the new 2-world shard) while still
broadcasting the params.

Invariant: grads of ones with lr=-1 SGD add exactly 1.0 to every
parameter element per step regardless of world size, so ``w == step``
at every commit. Surviving the reform with w intact proves the sharded
reduce-scatter/allgather data plane AND the shard-aware rollback, not
just the re-form handshake.
"""

import os
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

TOTAL_STEPS = int(os.environ.get("ELASTIC_TOTAL_STEPS", "8"))
# deliberately NOT divisible by 2 or 3: both the pre- and post-reform
# shards are zero-padded, so the resync slicing is exercised for real
N = 37

OPT = None


@elastic.run
def train(state):
    import jax.numpy as jnp
    import optax

    while state.step < TOTAL_STEPS:
        grads = {"w": jnp.ones((N,), jnp.float32)}
        updates, state.optimizer = OPT.update(
            grads, state.optimizer, state.params)
        state.params = optax.apply_updates(state.params, updates)
        state.step += 1
        state.commit()
    return state


def main() -> int:
    global OPT
    import jax.numpy as jnp
    import optax

    hvd.init()
    # lr=-1: optax.sgd emits updates == +grads, apply_updates ADDS them
    OPT = hvd.sharded_update(optax.sgd(-1.0))
    params = {"w": jnp.zeros((N,), jnp.float32)}
    state = elastic.ArrayState(
        params=params, optimizer=OPT.init(params), step=0)
    train(state)

    w_arr = np.asarray(state.params["w"])
    w = float(w_arr[0])
    restarts = elastic.restarts()
    from horovod_tpu.elastic.runner import _RESTARTS_TOTAL

    spec = state.optimizer.spec
    print(f"DONE rank={hvd.rank()} size={hvd.size()} step={state.step} "
          f"w={w:g} generation={restarts} "
          f"elastic_restarts_total={_RESTARTS_TOTAL.value:g} "
          f"shard_world={spec.world} shard_rank={spec.rank}",
          flush=True)
    if state.step != TOTAL_STEPS:
        return 3
    # every element moved in lockstep — not just [0]
    if not np.all(np.abs(w_arr - TOTAL_STEPS) < 1e-5):
        return 3
    # the re-sharded state must describe the CURRENT world, or the next
    # update would pack against a stale layout
    if spec.world != hvd.size() or spec.rank != hvd.rank():
        return 4
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
