#!/usr/bin/env python
"""Perf regression gate: diff two bench.py artifact trajectories.

The driver snapshots each round's ``python bench.py`` output as
``BENCH_rNN.json`` — ``{"n", "cmd", "rc", "tail"}`` where ``tail`` holds
the run's last stdout lines, a mix of log text and the one-JSON-line-per-
headline protocol (bench.py prints a cumulative ``summary`` line whose
``results`` array re-states every completed headline, so even an rc=124
truncated artifact carries everything that finished). This tool parses
both artifacts, matches headlines by metric name, and fails loudly when
the candidate regresses past the threshold:

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py --baseline BENCH_r05.json \
        --candidate /tmp/new.json --threshold-pct 3

Direction comes from the unit: rates (``*/sec*``), ``mfu`` and
``x``-factors are higher-is-better; ``ms``/``us``/``seconds``/``bytes``
are lower-is-better. Rows marked ``"tiny": true`` (smoke-test mode —
bench.py's own docs call the numbers meaningless) are ignored. The
embedded per-headline MFU, step-phase seconds (``step_breakdown``,
PR 6), serving tail latencies (p50/p99 request latency and TTFT,
``ms`` so lower-is-better), and comms bandwidth rows (``busbw_gbs`` /
``comms_utilization``, rates so higher-is-better — a deflated bus
bandwidth gates like a throughput regression) are compared as derived
sub-metrics; phases
under 1 ms are skipped (pure jitter at that scale). Exit status: 0 clean, 1 regression(s),
2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# derived step-phase rows below this baseline value are noise, not signal
MIN_PHASE_SECONDS = 1e-3

LOWER_IS_BETTER_UNITS = ("ms", "us", "seconds", "s", "bytes", "builds")


def parse_artifact(path: str) -> Dict[str, dict]:
    """Metric-name -> headline dict for one artifact. Later lines win
    (bench.py re-emits the cumulative summary after every workload), and
    a summary's ``results`` array is expanded so truncated runs still
    contribute every completed headline."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as exc:
            raise ValueError(f"{path}: not JSON: {exc}")
    if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
        lines = doc["tail"].splitlines()
    elif isinstance(doc, dict) and "metric" in doc:
        lines = [json.dumps(doc)]
    elif isinstance(doc, list):
        lines = [json.dumps(o) for o in doc]
    else:
        raise ValueError(f"{path}: no 'tail' field and not a headline "
                         "document")

    rows: Dict[str, dict] = {}

    def take(obj: dict) -> None:
        if not isinstance(obj, dict) or "metric" not in obj:
            return
        for sub in obj.get("results") or ():
            take(sub)
        if obj.get("tiny"):
            return
        if obj["metric"].startswith("summary"):
            return  # its results were expanded above; the row itself
            # just mirrors the flagship and would double-count it
        if isinstance(obj.get("value"), (int, float)):
            rows[obj["metric"]] = obj

    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        take(obj)
    return rows


def higher_is_better(metric: str, unit: Optional[str]) -> bool:
    u = (unit or "").strip().lower()
    if u in LOWER_IS_BETTER_UNITS:
        return False
    if metric.endswith("[mfu]") or "/sec" in u or u in ("x", ""):
        return True
    return True


def derived_rows(rows: Dict[str, dict]) -> Dict[str, Tuple[float, str]]:
    """Flatten headlines to comparable (value, unit) rows, adding the
    per-headline MFU, step-phase, and memory sub-metrics. Memory rows
    ("bytes" unit) are direction-aware via LOWER_IS_BETTER_UNITS: a
    watermark or per-subsystem footprint growth gates like a perf
    regression."""
    flat: Dict[str, Tuple[float, str]] = {}
    for metric, obj in rows.items():
        flat[metric] = (float(obj["value"]), obj.get("unit") or "")
        if isinstance(obj.get("mfu"), (int, float)):
            flat[f"{metric} [mfu]"] = (float(obj["mfu"]), "mfu")
        breakdown = obj.get("step_breakdown")
        if isinstance(breakdown, dict):
            for phase, seconds in breakdown.items():
                if isinstance(seconds, (int, float)):
                    flat[f"{metric} [{phase} seconds]"] = (
                        float(seconds), "seconds")
        per_chip = obj.get("bytes_per_chip")
        if isinstance(per_chip, dict):
            for subsystem, nbytes in per_chip.items():
                if isinstance(nbytes, (int, float)):
                    flat[f"{metric} [{subsystem} bytes]"] = (
                        float(nbytes), "bytes")
        # ZeRO per-stage rows (bench.py --sharded-optimizer): update
        # latency and every bytes-dimensioned row gate lower-is-better;
        # steady-state builds get the "builds" unit so a compile-cache
        # miss after warmup gates too; the stage-3 comm-hidden fraction
        # is a rate (higher-is-better)
        stages = obj.get("stages")
        if isinstance(stages, dict):
            for sname, row in stages.items():
                if not isinstance(row, dict):
                    continue
                if isinstance(row.get("update_p50_ms"), (int, float)):
                    flat[f"{metric} [{sname} update_p50_ms]"] = (
                        float(row["update_p50_ms"]), "ms")
                for key in ("grad_wire_bytes_per_step",
                            "wire_bytes_per_step"):
                    if isinstance(row.get(key), (int, float)):
                        flat[f"{metric} [{sname} {key}]"] = (
                            float(row[key]), "bytes")
                if isinstance(row.get("steady_state_builds"),
                              (int, float)):
                    flat[f"{metric} [{sname} steady_state_builds]"] = (
                        float(row["steady_state_builds"]), "builds")
                if isinstance(row.get("gather_hidden_fraction"),
                              (int, float)):
                    flat[f"{metric} [{sname} gather_hidden_fraction]"] = (
                        float(row["gather_hidden_fraction"]), "fraction")
                sub = row.get("bytes_per_chip")
                if isinstance(sub, dict):
                    for subsystem, nbytes in sub.items():
                        if isinstance(nbytes, (int, float)):
                            flat[f"{metric} [{sname} {subsystem} "
                                 f"bytes]"] = (float(nbytes), "bytes")
        if isinstance(obj.get("peak_hbm_bytes"), (int, float)):
            flat[f"{metric} [peak_hbm bytes]"] = (
                float(obj["peak_hbm_bytes"]), "bytes")
        if isinstance(obj.get("kv_cache_bytes_per_chip"), (int, float)):
            flat[f"{metric} [kv_cache bytes]"] = (
                float(obj["kv_cache_bytes_per_chip"]), "bytes")
        # paged KV cache (bench.py --serve under HOROVOD_SERVE_PAGED /
        # --prefix-heavy): prefix reuse is a rate — "fraction" makes it
        # higher-is-better, so a collapsed hit rate gates like a
        # throughput regression while kv_cache bytes gate growth above
        if isinstance(obj.get("prefix_hit_rate"), (int, float)):
            flat[f"{metric} [prefix_hit_rate]"] = (
                float(obj["prefix_hit_rate"]), "fraction")
        # serving tail latencies (bench.py --serve): "ms" unit makes them
        # lower-is-better, so a p99 blow-up gates even when tokens/s holds
        for key in ("p50_latency_ms", "p99_latency_ms",
                    "p50_ttft_ms", "p99_ttft_ms"):
            if isinstance(obj.get(key), (int, float)):
                flat[f"{metric} [{key}]"] = (float(obj[key]), "ms")
        # comms plane (bench.py comms_rows, docs/comms.md): bus bandwidth
        # and roofline utilization are rates — higher-is-better by
        # default, so a deflated busbw gates like a throughput regression
        if isinstance(obj.get("busbw_gbs"), (int, float)):
            flat[f"{metric} [busbw_gbs]"] = (
                float(obj["busbw_gbs"]), "GB/s")
        if isinstance(obj.get("comms_utilization"), (int, float)):
            flat[f"{metric} [comms_utilization]"] = (
                float(obj["comms_utilization"]), "fraction")
        # goodput ledger (bench.py goodput_rows, docs/goodput.md): the
        # productive fraction of wall-clock is higher-is-better — a
        # candidate that burns its steps on stalls or replays gates like
        # a throughput regression even when step latency holds
        if isinstance(obj.get("goodput_fraction"), (int, float)):
            flat[f"{metric} [goodput_fraction]"] = (
                float(obj["goodput_fraction"]), "fraction")
    return flat


def compare(baseline: Dict[str, Tuple[float, str]],
            candidate: Dict[str, Tuple[float, str]],
            threshold_pct: float) -> Tuple[List[str], List[str]]:
    """Returns (report lines, regression lines)."""
    report: List[str] = []
    regressions: List[str] = []
    common = sorted(set(baseline) & set(candidate))
    for metric in common:
        base, unit = baseline[metric]
        cand, _ = candidate[metric]
        if unit == "seconds" and base < MIN_PHASE_SECONDS:
            continue
        if base == 0:
            continue
        delta_pct = (cand - base) / abs(base) * 100.0
        hib = higher_is_better(metric, unit)
        worse_pct = -delta_pct if hib else delta_pct
        verdict = "REGRESSION" if worse_pct > threshold_pct else "ok"
        line = (f"{verdict:>10}  {metric}: {base:g} -> {cand:g} {unit} "
                f"({delta_pct:+.2f}%, {'higher' if hib else 'lower'} is "
                f"better, threshold {threshold_pct:g}%)")
        report.append(line)
        if verdict == "REGRESSION":
            regressions.append(line)
    only_base = sorted(set(baseline) - set(candidate))
    only_cand = sorted(set(candidate) - set(baseline))
    for metric in only_base:
        report.append(f"{'missing':>10}  {metric}: in baseline only "
                      "(not compared)")
    for metric in only_cand:
        report.append(f"{'new':>10}  {metric}: in candidate only "
                      "(not compared)")
    return report, regressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Fail when a bench.py artifact regresses vs a "
                    "baseline artifact.")
    parser.add_argument("files", nargs="*",
                        help="BASELINE CANDIDATE (positional form)")
    parser.add_argument("--baseline", help="baseline BENCH_*.json")
    parser.add_argument("--candidate", help="candidate BENCH_*.json")
    parser.add_argument("--threshold-pct", type=float, default=5.0,
                        help="worsening beyond this %% fails the gate "
                             "(default 5; rates/MFU measured round-to-"
                             "round jitter is well under that)")
    args = parser.parse_args(argv)

    baseline_path = args.baseline
    candidate_path = args.candidate
    positional = list(args.files)
    if baseline_path is None and positional:
        baseline_path = positional.pop(0)
    if candidate_path is None and positional:
        candidate_path = positional.pop(0)
    if positional or baseline_path is None or candidate_path is None:
        parser.print_usage(sys.stderr)
        sys.stderr.write("bench_compare: need exactly a baseline and a "
                         "candidate artifact\n")
        return 2

    try:
        base_rows = derived_rows(parse_artifact(baseline_path))
        cand_rows = derived_rows(parse_artifact(candidate_path))
    except (OSError, ValueError) as exc:
        sys.stderr.write(f"bench_compare: {exc}\n")
        return 2
    if not base_rows:
        sys.stderr.write(f"bench_compare: no headline rows in "
                         f"{baseline_path!r}\n")
        return 2
    if not cand_rows:
        sys.stderr.write(f"bench_compare: no headline rows in "
                         f"{candidate_path!r}\n")
        return 2

    report, regressions = compare(base_rows, cand_rows,
                                  args.threshold_pct)
    compared = sum(1 for line in report
                   if line.lstrip().startswith(("ok", "REGRESSION")))
    print(f"bench_compare: {baseline_path} -> {candidate_path} "
          f"({compared} compared metrics)")
    for line in report:
        print(line)
    if regressions:
        print(f"bench_compare: {len(regressions)} regression(s) past "
              f"{args.threshold_pct:g}%", file=sys.stderr)
        return 1
    if not compared:
        sys.stderr.write("bench_compare: artifacts share no comparable "
                         "metrics\n")
        return 2
    print("bench_compare: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
