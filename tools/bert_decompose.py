#!/usr/bin/env python
"""Where does the BERT-Large MLM step actually go? (VERDICT r3 ask 1.)

Applies the ResNet evidentiary protocol (tools/resnet_decompose.py) to
the transformer headline: slope-timed chains (dispatch cancelled, salted
inputs against the tunnel memoizer, true data dependencies between scan
iterations against loop-invariant hoisting) on the bench configuration —
BERT-Large, batch 8/chip, seq 512, bf16, Pallas flash attention.

Phases measured:
  * trunk        — embed + 24 layers + final norm (NO vocab projection)
  * fwd          — trunk + tied vocab projection + masked-LM loss
  * grad         — jax.value_and_grad of fwd (fwd + bwd)
  * full         — grad + adamw update (bench.py's op)
  * attn         — 24 isolated flash-attention calls fwd (bench shapes)
  * attn_grad    — the same 24 calls fwd + bwd

Derived:  vocab+loss = fwd - trunk;  bwd = grad - fwd;  opt = full - grad;
MLP+LN+embed trunk time = trunk - attn.

``--only PHASE`` measures a single phase (a tunnel hiccup then only
loses one variant; drive the set from a shell loop). The counter-moves
themselves (masked-position gather, bf16 adam moments, fused qkv) live
as model/bench options — ``masked_lm_loss_gathered`` +
``Transformer(..., output="hidden")``, ``BENCH_MLM_GATHER``,
``BENCH_ADAM_MU_BF16`` in bench.py — and are A/B-measured there, where
the headline protocol already runs.

Every number is a median of slope rounds: t(2N chains) - t(N chains)
over N extra iterations, so compile, dispatch, and readback cancel.
"""

import argparse
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.models.transformer import BertLarge, masked_lm_loss  # noqa: E402
from horovod_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402

BATCH = 8
SEQ = 512
VOCAB = 30522
D_MODEL, N_LAYERS, N_HEADS, D_FF = 1024, 24, 16, 4096
HEAD_DIM = D_MODEL // N_HEADS
PREDICTIONS_PER_SEQ = 76  # BERT's max_predictions_per_seq for seq 512
ITERS = 10
ROUNDS = 6
PEAK = 197e12  # v5e bf16


def flops_per_token(n_params):
    attn = 12 * N_LAYERS * SEQ * D_MODEL
    return 6 * n_params + attn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    choices=["vocab", "fwd", "grad", "full", "attn",
                             "attn_grad", "opt"],
                    help="measure ONE phase (a tunnel hiccup then only "
                         "loses one variant; drive the set from a shell "
                         "loop)")
    args = ap.parse_args()

    model = BertLarge(vocab_size=VOCAB, max_seq=SEQ, dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32))
    mask = jnp.asarray((rng.rand(BATCH, SEQ) < 0.15).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), tokens[:1], train=False)
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    step_flops = flops_per_token(n_params) * BATCH * SEQ
    fwd_flops = step_flops / 3.0

    # -- chained variants (each iteration depends on the previous one's
    # scalar output, so XLA cannot hoist the body out of the scan) -----

    def shift_from(x):
        # data-dependent roll: cheap (16 KB gather) but a true dependency
        return (jnp.abs(x) * 1e4).astype(jnp.int32) % SEQ

    def loss_fn(p, toks, msk):
        logits = model.apply(p, toks, train=True)
        return masked_lm_loss(logits, toks, msk)

    # isolate the vocab projection + MLM loss on a FIXED hidden-state
    # tensor (the model's tied projection is hidden @ E^T with E the
    # token embedding, models/transformer.py:178): trunk time falls out
    # as fwd - vocab_loss without re-entering flax
    embed_matrix = params["params"]["token_embed"]["embedding"]
    hidden0 = jnp.asarray(rng.randn(BATCH, SEQ, D_MODEL), jnp.bfloat16)

    @partial(jax.jit, static_argnames="iters")
    def vocab_loss_chain(emb, h, toks, msk, salt, iters):
        def body(h_c, _):
            logits = (h_c @ emb.astype(jnp.bfloat16).T).astype(jnp.float32)
            loss = masked_lm_loss(logits, toks, msk)
            return h_c * (1 + 1e-9 * (loss + salt)).astype(h_c.dtype), loss

        _, losses = jax.lax.scan(body, h, None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def fwd_chain(p, toks, msk, salt, iters):
        def body(carry, _):
            toks_c = carry
            loss = loss_fn(p, toks_c, msk)
            return jnp.roll(toks_c, shift_from(loss + salt), axis=1), loss

        _, losses = jax.lax.scan(body, toks, None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def grad_chain(p, toks, msk, salt, iters):
        def body(carry, _):
            p_c = carry
            loss, g = jax.value_and_grad(loss_fn)(p_c, toks, msk)
            # consume the gradient without an optimizer: fold a scaled
            # copy back into the params (keeps the whole bwd alive)
            p_c = jax.tree_util.tree_map(
                lambda a, b: a - 1e-9 * b.astype(a.dtype), p_c, g)
            return p_c, loss + salt

        _, losses = jax.lax.scan(body, params, None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def full_chain(p, o, toks, msk, salt, iters):
        def body(carry, _):
            p_c, o_c = carry
            loss, g = jax.value_and_grad(loss_fn)(p_c, toks, msk)
            upd, o_c = tx.update(g, o_c, p_c)
            p_c = optax.apply_updates(p_c, upd)
            return (p_c, o_c), loss + salt

        _, losses = jax.lax.scan(body, (p, o), None, length=iters)
        return losses[-1]

    # isolated attention at the bench shape (all 24 layers' worth)
    q0 = jnp.asarray(rng.randn(BATCH, N_HEADS, SEQ, HEAD_DIM),
                     jnp.bfloat16)
    k0 = jnp.asarray(rng.randn(BATCH, N_HEADS, SEQ, HEAD_DIM),
                     jnp.bfloat16)
    v0 = jnp.asarray(rng.randn(BATCH, N_HEADS, SEQ, HEAD_DIM),
                     jnp.bfloat16)

    @partial(jax.jit, static_argnames="iters")
    def opt_chain(p, o, g0, salt, iters):
        # adamw update alone, chained through the params (grads fixed):
        # isolates the optimizer's HBM traffic (read p+mu+nu+g, write
        # p+mu+nu) without the model in the program, so the compile is
        # small enough to survive tunnel hiccups. bwd then falls out of
        # full - fwd - opt when the grad phase is unavailable.
        def body(carry, _):
            p_c, o_c = carry
            upd, o_c = tx.update(g0, o_c, p_c)
            p_c = optax.apply_updates(p_c, upd)
            p_c = jax.tree_util.tree_map(
                lambda a: a + jnp.asarray(salt * 1e-12, a.dtype), p_c)
            return (p_c, o_c), 0.0
        (p_f, _), _ = jax.lax.scan(body, (p, o), None, length=iters)
        # reduce over EVERY element — adamw is elementwise, so returning
        # a single element would let XLA slice the whole update to one
        # lane (measured: the step collapses to ~0)
        return sum(jnp.sum(leaf) for leaf in jax.tree_util.tree_leaves(p_f))

    @partial(jax.jit, static_argnames="iters")
    def attn_chain(q, k, v, salt, iters):
        def body(q_c, _):
            x = q_c
            for _ in range(N_LAYERS):
                x = flash_attention(x, k, v, causal=False)
            out = jnp.mean(x[:, 0, 0, :].astype(jnp.float32))
            return q_c + (1e-6 * out + salt).astype(q_c.dtype), out

        _, outs = jax.lax.scan(body, q, None, length=iters)
        return outs[-1]

    @partial(jax.jit, static_argnames="iters")
    def attn_grad_chain(q, k, v, salt, iters):
        def attn_loss(q_c):
            x = q_c
            for _ in range(N_LAYERS):
                x = flash_attention(x, k, v, causal=False)
            return jnp.mean(x.astype(jnp.float32))

        def body(q_c, _):
            out, g = jax.value_and_grad(attn_loss)(q_c)
            # salt must survive into the executable (an arg XLA drops
            # would let the tunnel memoize identical calls)
            return (q_c - 1e-6 * g.astype(q_c.dtype)
                    + jnp.asarray(salt * 1e-12, q_c.dtype)), out

        _, outs = jax.lax.scan(body, q, None, length=iters)
        return outs[-1]

    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    def measure(fn, *fnargs):
        for iters in (ITERS, 2 * ITERS):  # compile both lengths
            float(fn(*fnargs, fresh_salt(), iters=iters))
        slopes = []
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            float(fn(*fnargs, fresh_salt(), iters=ITERS))
            t1 = time.perf_counter()
            float(fn(*fnargs, fresh_salt(), iters=2 * ITERS))
            t2 = time.perf_counter()
            slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
        return float(np.median(slopes))

    res = {"batch": BATCH, "seq": SEQ, "n_params_m": round(n_params / 1e6, 1)}

    variants = {
        "vocab": lambda: measure(vocab_loss_chain, embed_matrix, hidden0,
                                 tokens, mask),
        "fwd": lambda: measure(fwd_chain, params, tokens, mask),
        "grad": lambda: measure(grad_chain, params, tokens, mask),
        "full": lambda: measure(full_chain, params, opt_state, tokens,
                                mask),
        "opt": lambda: measure(
            opt_chain, params, opt_state,
            jax.tree_util.tree_map(
                lambda a: jnp.full_like(a, 1e-6), params)),
        "attn": lambda: measure(attn_chain, q0, k0, v0),
        "attn_grad": lambda: measure(attn_grad_chain, q0, k0, v0),
    }
    if args.only:
        t = variants[args.only]()
        res[f"{args.only}_ms"] = round(t * 1e3, 2)
        if args.only == "full":
            res["full_step_mfu"] = round(step_flops / t / PEAK, 4)
            res["tokens_per_sec"] = round(BATCH * SEQ / t, 1)
        if args.only == "fwd":
            res["fwd_mfu"] = round(fwd_flops / t / PEAK, 4)
        print(json.dumps(res), flush=True)
        return

    t_vocab = variants["vocab"]()
    t_fwd = variants["fwd"]()
    t_grad = variants["grad"]()
    t_full = variants["full"]()
    t_attn = variants["attn"]()
    t_attn_grad = variants["attn_grad"]()

    res.update({
        "vocab_loss_fwd_ms": round(t_vocab * 1e3, 2),
        "trunk_fwd_ms": round((t_fwd - t_vocab) * 1e3, 2),
        "fwd_ms": round(t_fwd * 1e3, 2),
        "grad_ms": round(t_grad * 1e3, 2),
        "full_step_ms": round(t_full * 1e3, 2),
        "attn_fwd_24x_ms": round(t_attn * 1e3, 2),
        "attn_grad_24x_ms": round(t_attn_grad * 1e3, 2),
        "bwd_ms": round((t_grad - t_fwd) * 1e3, 2),
        "opt_update_ms": round((t_full - t_grad) * 1e3, 2),
        "fwd_mfu": round(fwd_flops / t_fwd / PEAK, 4),
        "full_step_mfu": round(step_flops / t_full / PEAK, 4),
        "tokens_per_sec": round(BATCH * SEQ / t_full, 1),
    })
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
