#!/usr/bin/env python
"""Does tensor fusion actually engage THROUGH the framework bindings?

VERDICT r3 ask 6: ``tools/control_plane_bench.py`` proves the runtime's
fusion/cache win by driving the named numpy API directly — but a user
reaches the runtime through the torch hook optimizer or the TF gradient
tape, and nothing measured whether those paths arrive at the runtime as
a fusable burst or as serialized one-at-a-time ops (they did serialize
through TF until the grouped-allreduce bridge; this harness is the
regression net).

A ~50-parameter model steps at np=2 through
  (a) the torch path: hvd.DistributedOptimizer, gradient hooks firing
      async in-place allreduces during backward, step() synchronizing
      (torch/__init__.py:60-170), and
  (b) the TF path: tf.GradientTape -> hvd.DistributedGradientTape,
      dense grads riding the grouped-allreduce py_function
      (tensorflow/__init__.py _make_allreduce_grads_fn),
reporting the DETERMINISTIC per-step protocol counters (ring-kernel
exchanges + control-plane bytes from the native transport) for the
default config vs HOROVOD_FUSION_THRESHOLD=0. Wall time on a 1-core CI
box measures the scheduler; the counters are box-independent.

Run:  python tools/binding_fusion_bench.py [--np 2]
Emits one JSON object on stdout.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

# the container's sitecustomize force-selects the TPU platform; these
# host-side processes must stay on CPU (and off the single real chip) —
# both the env AND the config update are needed, before anything imports
# jax machinery (tests/mp_worker.py does the same)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PARAMS = 50     # small tensors per step (the fusion-relevant regime)
STEPS = 10
WARMUP = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def worker() -> None:
    sys.path.insert(0, REPO)
    import horovod_tpu.torch as thvd
    import torch

    from horovod_tpu.core import state

    thvd.init()
    rank = thvd.rank()
    results = {}

    def measure(label, one_step):
        for _ in range(WARMUP):
            one_step()
        net = state.global_state().runtime.controller.net
        ctrl0, ex0 = net.ctrl_bytes_sent(), net.exchange_calls()
        t0 = time.perf_counter()
        for _ in range(STEPS):
            one_step()
        dt = time.perf_counter() - t0
        d_ex = net.exchange_calls() - ex0
        d_ctrl = net.ctrl_bytes_sent() - ctrl0
        if d_ex < 0 or d_ctrl < 0:
            # counters read 0 from a closed Comm handle — the world shut
            # down mid-measure (a peer died); fail loudly, never report
            # garbage deltas
            raise RuntimeError(
                f"{label}: counter went backwards (d_ex={d_ex}, "
                f"d_ctrl={d_ctrl}) — world shut down mid-measure")
        results[label] = {
            "exchanges_per_step": d_ex / STEPS,
            "ctrl_bytes_per_step": d_ctrl / STEPS,
            "ms_per_step": dt / STEPS * 1e3,
        }

    # (a) torch hook optimizer: N_PARAMS small weights, hooks fire
    # during backward, step() syncs
    torch.manual_seed(0)  # identical init everywhere
    model = torch.nn.ModuleList(
        [torch.nn.Linear(9, 1) for _ in range(N_PARAMS // 2)])
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1e-3),
        named_parameters=model.named_parameters())
    x = torch.randn(4, 9)

    def torch_step():
        opt.zero_grad()
        loss = sum(m(x).sum() for m in model) * (rank + 1)
        loss.backward()
        opt.step()

    measure("torch", torch_step)

    # (b) TF tape: same parameter count through DistributedGradientTape
    import tensorflow as tf

    import horovod_tpu.tensorflow as tfhvd

    weights = [tf.Variable(tf.fill([7 + (i % 5)], float(i + 1)))
               for i in range(N_PARAMS)]

    def tf_step():
        with tf.GradientTape() as tape:
            loss = tf.add_n([tf.reduce_sum(w * w) * (rank + 1)
                             for w in weights])
        dtape = tfhvd.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, weights)
        for w, g in zip(weights, grads):
            w.assign_sub(1e-3 * g)

    measure("tf", tf_step)

    # Quiesce before shutdown: shutdown is NOT a barrier (reference
    # semantics match), so a rank that finishes first and closes its
    # sockets kills a peer whose last burst completion is still in
    # flight — observed as this tool's flaky negative-counter /
    # shut-down-mid-measure failures. A synchronous allreduce returns
    # only once every prior op on the ordered lane completed on ALL
    # ranks, so after it no rank has in-flight work.
    thvd.allreduce(torch.zeros(1), name="fb.quiesce")
    thvd.shutdown()
    if rank == 0:
        print("RESULTS " + json.dumps(results), flush=True)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def launch(world: int, extra_env: dict, timeout: float = 420.0):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_CONTROLLER": "socket",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
        })
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            if p.returncode != 0:
                raise RuntimeError(f"worker failed rc={p.returncode}:\n{out}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULTS "):
                return json.loads(line[len("RESULTS "):])
    raise RuntimeError("no RESULTS line from rank 0:\n" + "\n".join(outs))


def main(world: int) -> dict:
    fused = launch(world, {})
    unfused = launch(world, {"HOROVOD_FUSION_THRESHOLD": "0"})
    out = {"world": world, "params_per_step": N_PARAMS}
    for path in ("torch", "tf"):
        f, u = fused[path], unfused[path]
        out[path] = {
            "exchanges_per_step_fused": round(f["exchanges_per_step"], 2),
            "exchanges_per_step_unfused": round(u["exchanges_per_step"], 2),
            "fusion_dispatch_reduction_x": round(
                u["exchanges_per_step"]
                / max(f["exchanges_per_step"], 1e-9), 2),
            "ctrl_bytes_per_step_fused": round(f["ctrl_bytes_per_step"], 1),
            "ctrl_bytes_per_step_unfused": round(
                u["ctrl_bytes_per_step"], 1),
            "ms_per_step_fused": round(f["ms_per_step"], 2),
            "ms_per_step_unfused": round(u["ms_per_step"], 2),
        }
    return out


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--np", type=int, default=2)
    cli = parser.parse_args()
    if cli.worker:
        worker()
    else:
        print(json.dumps(main(cli.np)), flush=True)
