#!/usr/bin/env python
"""Network-chaos acceptance matrix (ISSUE 8).

Runs the fault-mode × phase matrix as real multiprocess scenarios over
the socket/native transport — the rendezvous HTTP store lives in this
process, standing in for the tpurun launcher — and emits ONE JSON
summary on stdout. Exit status 0 only when every scenario meets its
expectations; any unexpected worker death (or a missed invariant) exits
1.

Scenarios (docs/robustness.md has the failure-model table):

* ``flaky_negotiate``   — ``flaky:0.3`` during negotiate: training
  completes with zero lost steps and nonzero retries.
* ``netdelay_negotiate``— fixed per-op latency: completes, injections
  counted, and every rank's shutdown dump embeds the comms-plane ledger
  (the ``comms`` state provider, docs/comms.md) with recorded host-ring
  traffic, rendered by the merged postmortem's comms report.
* ``kv_outage_reform``  — rank 1 killed at step 3 while the rendezvous
  store answers 503 for 5s starting at the first re-form registration:
  survivors bridge the outage and finish.
* ``partition_collective_timeout`` — a permanent partition of rank 1
  mid-run: survivors trip HOROVOD_COLLECTIVE_TIMEOUT, re-form within
  the deadline, finish, and the merged flight-recorder postmortem names
  the partitioned rank.
* ``hier_cross_kill``   — ISSUE 18: two ranks of a six-rank
  hierarchical world (3 groups of 2, netdelay-throttled cross hop)
  killed mid-run; survivors re-form at world 4, the executor recomputes
  the groups (2x2) for the new world, and training finishes with zero
  lost steps.

Checkpoint crash-consistency scenarios (ISSUE 9; docs/checkpointing.md):

* ``ckpt_kill_mid_commit`` — rank 1 killed at the PUBLISH phase of the
  step-3 two-phase commit (after its shard rename, before its
  ``published`` announcement; ``CHAOS_CKPT_PHASE=stage|barrier``
  re-aims the kill at the other protocol points — the invariant is the
  same at every phase): the leader abandons the step-3 manifest,
  the survivors re-form and finish, and afterwards EVERY manifest in
  the directory restores bit-identically (``w == step`` exactly) while
  no step-3 manifest exists — a kill mid-commit can never corrupt or
  publish a partial cut.
* ``ckpt_reform_sharded_adamw`` — rank 1 killed at training step 3
  under ZeRO-1 sharded AdamW: after the re-form the dead rank's
  fp32 moment segments are restored from its left neighbor's replica
  (nonzero, uniform across shards), not zero-filled.

Numerical-integrity scenarios (ISSUE 10; docs/integrity.md):

* ``integrity_bitflip_rollback`` — a one-shot bit flip corrupts rank 1's
  copy of the 5th allreduce result: the per-dispatch digest exchange
  detects the CRC divergence, the cross-rank vote names rank 1, every
  rank rolls back IN PLACE (no process restart, no re-form) to the
  step-4 checkpoint and replays — training finishes with ``w == step``
  bit-identical to an uninjected run, and the merged postmortem names
  the flipped rank.
* ``integrity_nan_skipstep`` — a one-shot NaN poisons rank 1's
  contribution to the 5th allreduce with digests disabled, so the NaN
  reaches every rank's reduced gradient: the step-level spike guard
  skips that step in lockstep (one retry, nothing applied or
  committed) and training converges to the exact final weights.

Goodput-attribution scenario (ISSUE 19; docs/goodput.md):

* ``goodput_attribution`` — one three-rank run, three disruptions: a
  one-shot bit flip on rank 2's 3rd allreduce (in-place rollback +
  replay), then rank 1 killed at step 5 while the rendezvous store
  answers 503 for 5s at the first re-form registration. Every
  survivor's goodput ledger must account >= 90% of its wall-clock, the
  replayed step(s) land in ``rollback`` badput (not productive time),
  the re-form downtime lands in ``elastic_reform``, and the merged
  postmortem's goodput report names the costliest incident and its
  culprit rank.

Serving-plane scenario (ISSUE 11; docs/inference.md):

* ``serve_kill_replica`` — rank 0 drives Poisson-ish load through a
  :class:`KVQueueFrontend` at three serving replicas; rank 2 is killed
  at its 5th decode step, mid-generation. The survivors absorb the
  traffic (the frontend re-dispatches on the lapsed heartbeat), every
  submitted request completes (``zero_lost``), the redistribution
  really happened (``requeued`` nonzero), and the postmortem names the
  dead rank. Needs no native transport — the serving plane rides the
  rendezvous KV store alone.

Usage: python tools/chaos_matrix.py [--only NAME] [--json PATH]
"""

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from horovod_tpu import flight_recorder  # noqa: E402
from horovod_tpu.run.rendezvous import RendezvousServer  # noqa: E402
from horovod_tpu.runtime.native import native_built  # noqa: E402

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


SCENARIOS = {
    "flaky_negotiate": {
        "world": 2,
        "env": {
            "HOROVOD_FAULT_INJECT": "flaky:0.3:seconds=8",
            # 0.3^k exhaustion over thousands of control rounds needs a
            # deeper per-op attempt budget than the default 4
            "HOROVOD_NET_MAX_RETRIES": "12",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "CHAOS_STEP_SLEEP": "0.2",
        },
        "require_retries": True,
        "timeout": 180,
    },
    "netdelay_negotiate": {
        "world": 2,
        "env": {
            "HOROVOD_FAULT_INJECT": "netdelay:10",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "require_injections": True,
        "require_comms_state": True,
        "timeout": 180,
    },
    "kv_outage_reform": {
        "world": 3,
        "env": {
            "HOROVOD_FAULT_INJECT":
                "kill:rank=1:step=3:code=17;kv_outage:5:on=reform",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "expected_exit": {1: 17},
        "require_retries": True,
        "require_reform": True,
        "timeout": 240,
    },
    # ISSUE 18: ranks killed while the hierarchical allreduce is inside
    # its (netdelay-throttled) cross-group exchange. The six-rank world
    # runs 3 groups of 2; after the two kills the survivors re-form at
    # world 4 and the executor must RECOMPUTE the groups (2x2, not the
    # stale 3x2 plan keyed to the dead transport) and finish with zero
    # lost steps. The intermediate world of 5 exercises the flat
    # fallback (5 % 2 != 0) on the way down.
    "hier_cross_kill": {
        "world": 6,
        "env": {
            "HOROVOD_FAULT_INJECT":
                "netdelay:5:hop=cross;"
                "kill:rank=4:step=3:code=17;"
                "kill:rank=5:step=5:code=19:gen=1",
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_HIERARCHY_GROUP_SIZE": "2",
            "HOROVOD_ELASTIC_MIN_WORKERS": "4",
        },
        "expected_exit": {4: 17, 5: 19},
        "require_injections": True,
        "require_reform": True,
        "require_true": ("hier_enabled",),
        "require_hier_groups": 2,
        "timeout": 300,
    },
    "partition_collective_timeout": {
        "world": 3,
        "env": {
            "HOROVOD_FAULT_INJECT": "partition:1:600:after=4",
            "HOROVOD_COLLECTIVE_TIMEOUT": "4",
            "HOROVOD_GLOO_TIMEOUT_SECONDS": "8",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "CHAOS_STEP_SLEEP": "1.0",
        },
        "hung_ranks": [1],
        "require_reform": True,
        "require_culprit": 1,
        "timeout": 240,
    },
    "ckpt_kill_mid_commit": {
        "world": 3,
        "ckpt": True,
        "env": {
            # CHAOS_CKPT_PHASE widens the cell to the other protocol
            # points (stage / barrier) without a separate scenario:
            # the acceptance invariant is phase-independent
            "HOROVOD_CKPT_FAULT":
                "kill:rank=1:phase="
                + os.environ.get("CHAOS_CKPT_PHASE", "publish")
                + ":step=3:code=19",
            "HOROVOD_CKPT_ASYNC": "0",
            "HOROVOD_CKPT_KEEP": "20",
            "HOROVOD_CKPT_BARRIER_TIMEOUT_SECONDS": "3",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "expected_exit": {1: 19},
        "require_reform": True,
        "ckpt_verify": "midcommit",
        "timeout": 240,
    },
    "ckpt_reform_sharded_adamw": {
        "world": 3,
        "worker": "ckpt_chaos_worker.py",
        "ckpt": True,
        "env": {
            "HOROVOD_FAULT_INJECT": "kill:rank=1:step=3:code=17",
            "HOROVOD_CKPT_ASYNC": "0",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "expected_exit": {1: 17},
        "require_reform": True,
        "check_w": False,
        "require_true": ["steps_ok", "moments_nonzero",
                         "moments_uniform", "replica_restored"],
        "ckpt_verify": "manifest",
        "timeout": 240,
    },
    # ISSUE 20: rank 1 killed INSIDE a stage-2 bucket reduce-scatter —
    # bucket 0's reduce-scatter already in flight, later buckets never
    # released. The survivors' gather fails the orphaned stage-2 tokens
    # with WorkersDownError, the re-formed 2-worker generation resyncs
    # the sharded AdamW shards to the new world, training reaches the
    # expected weights, and no fusion-buffer lease leaks.
    "zero2_kill_mid_reducescatter": {
        "world": 3,
        "worker": "zero2_chaos_worker.py",
        "env": {
            "ZERO2_KILL_STEP": "3",
            "ZERO2_KILL_RANK": "1",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "expected_exit": {1: 17},
        "require_reform": True,
        "require_true": ["resharded", "leases_ok"],
        "timeout": 240,
    },
    "serve_kill_replica": {
        "world": 4,   # rank 0 = frontend/loadgen, ranks 1-3 = replicas
        "worker": "serve_chaos_worker.py",
        "env": {
            "HOROVOD_FAULT_INJECT": "kill:rank=2:step=5:code=21",
            "HOROVOD_SERVE_SLOTS": "4",
            "HOROVOD_SERVE_MAX_NEW_TOKENS": "16",
            "HOROVOD_SERVE_DECODE_BLOCK": "4",
            "HOROVOD_SERVE_ADMISSION_MS": "10",
        },
        "expected_exit": {2: 21},
        "check_w": False,
        "require_true": ["zero_lost", "requeued"],
        "require_culprit": 2,
        "timeout": 240,
    },
    "integrity_bitflip_rollback": {
        "world": 3,
        "ckpt": True,
        "env": {
            "HOROVOD_FAULT_INJECT": "bitflip:1:after=4",
            "HOROVOD_INTEGRITY": "1",
            "HOROVOD_INTEGRITY_INTERVAL": "1",
            "HOROVOD_CKPT_ASYNC": "0",
            "HOROVOD_ELASTIC_MIN_WORKERS": "3",
        },
        "require_true": ["integrity_violations", "rollbacks"],
        "require_culprit": 1,
        "ckpt_verify": "manifest",
        "timeout": 240,
    },
    # ISSUE 19: the goodput-attribution proof. Fault order: bitflip at
    # the 3rd dispatch (step 3, world still 3 so the digest vote can
    # convict), kill at step 5, kv outage bracketing the re-form. The
    # per-rank ledger assertions live in the require_goodput block of
    # run_scenario.
    "goodput_attribution": {
        "world": 3,
        "ckpt": True,
        "env": {
            "HOROVOD_FAULT_INJECT":
                "bitflip:2:after=2;"
                "kill:rank=1:step=5:code=17;"
                "kv_outage:5:on=reform",
            "HOROVOD_INTEGRITY": "1",
            "HOROVOD_INTEGRITY_INTERVAL": "1",
            "HOROVOD_CKPT_ASYNC": "0",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "CHAOS_STEP_SLEEP": "0.2",
        },
        "expected_exit": {1: 17},
        "require_retries": True,
        "require_reform": True,
        "require_true": ["integrity_violations", "rollbacks"],
        "require_goodput": True,
        "ckpt_verify": "manifest",
        "timeout": 240,
    },
    "integrity_nan_skipstep": {
        "world": 2,
        "env": {
            "HOROVOD_FAULT_INJECT": "nan:1:after=4",
            "HOROVOD_INTEGRITY": "1",
            # digests off: the nan flows through the ring to every rank
            # and the step-level guard (not the collective plane) must
            # catch it
            "HOROVOD_INTEGRITY_INTERVAL": "0",
            "CHAOS_INTEGRITY_GUARD": "1",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "require_true": ["skipped_steps"],
        "timeout": 180,
    },
}


def _verify_ckpt_midcommit(ckpt_dir, total, failures):
    """Every manifest left behind restores bit-identically (the loop
    adds exactly 1.0 per step, so ``w == float32(step)`` exactly), the
    abandoned step-3 manifest does not exist, and the newest cut is the
    final step."""
    import numpy as np

    from horovod_tpu import ckpt
    from horovod_tpu.ckpt import manifest as mf_mod

    steps = mf_mod.all_steps(ckpt_dir)
    if 3 in steps:
        failures.append(
            "step-3 manifest exists — the publish-phase kill should "
            "have abandoned that commit")
    if not steps or max(steps) != total:
        failures.append(
            f"newest manifest is {max(steps) if steps else None}, "
            f"want {total} (steps: {steps})")
    target = {"params": {"w": np.zeros(4, np.float32)}, "optimizer": None}
    for s in steps:
        try:
            trees, _ = ckpt.restore_step(ckpt_dir, s, target)
        except Exception as exc:
            failures.append(f"restore_step({s}) failed: {exc}")
            continue
        w = np.asarray(trees["params"]["w"])
        if not np.array_equal(w, np.full(4, np.float32(s))):
            failures.append(
                f"step {s} restored w={w.tolist()} — not bit-identical "
                f"to the committed value {float(s)}")


def _verify_ckpt_manifest(ckpt_dir, total, failures):
    """The newest manifest is the final step and every shard file it
    names passes its whole-file digest."""
    from horovod_tpu.ckpt import manifest as mf_mod

    steps = mf_mod.all_steps(ckpt_dir)
    if not steps or max(steps) != total:
        failures.append(
            f"newest manifest is {max(steps) if steps else None}, "
            f"want {total} (steps: {steps})")
        return
    try:
        manifest = mf_mod.load_manifest(ckpt_dir, max(steps))
        mf_mod.verify_manifest_files(ckpt_dir, manifest)
    except Exception as exc:
        failures.append(f"final manifest failed verification: {exc}")


def _collect_dumps(flight_dir, server):
    """Local flight-rank-*.json files + dumps shipped to the rendezvous
    ``flight`` scope, deduplicated by launch rank (shipped wins — it is
    at least as recent as the file)."""
    by_rank = {}
    for d in flight_recorder.load_dumps(flight_dir):
        by_rank[d.get("launch_rank", d.get("rank"))] = d
    for key in server.live_keys(flight_recorder.RENDEZVOUS_SCOPE):
        raw = server.get(flight_recorder.RENDEZVOUS_SCOPE, key)
        try:
            d = json.loads(raw)
        except (TypeError, ValueError):
            continue
        by_rank[d.get("launch_rank", d.get("rank"))] = d
    return list(by_rank.values())


def run_scenario(name, spec):
    world = spec["world"]
    timeout = spec.get("timeout", 240)
    hung = set(spec.get("hung_ranks", ()))
    expected_exit = dict(spec.get("expected_exit", {}))
    worker = os.path.join(REPO, "tools",
                          spec.get("worker", "chaos_worker.py"))
    flight_dir = tempfile.mkdtemp(prefix="chaos-flight-")
    ckpt_dir = (tempfile.mkdtemp(prefix="chaos-ckpt-")
                if spec.get("ckpt") else None)
    server = RendezvousServer(host="127.0.0.1")
    http_port = server.start()
    socket_port = _free_port()
    procs = []
    outs = [""] * world
    failures = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "HOROVOD_FLIGHT_RECORDER_DIR": flight_dir,
                "JAX_PLATFORMS": "cpu",
            })
            if ckpt_dir:
                env["HOROVOD_CKPT_DIR"] = ckpt_dir
            env.update(spec.get("env", {}))
            procs.append(subprocess.Popen(
                [sys.executable, worker], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        # wait for every rank that is expected to terminate on its own;
        # a permanently-partitioned rank blocks forever by design and is
        # reaped after the survivors finish
        deadline = time.monotonic() + timeout
        waiting = {i for i in range(world) if i not in hung}
        while waiting and time.monotonic() < deadline:
            for i in sorted(waiting):
                if procs[i].poll() is not None:
                    waiting.discard(i)
            time.sleep(0.2)
        for i in sorted(waiting):
            failures.append(f"rank {i} did not finish within {timeout}s")
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
                if i not in hung and i in waiting:
                    pass  # already reported as a timeout above
            out, _ = p.communicate(timeout=30)
            outs[i] = out or ""

        results = {}
        for i, out in enumerate(outs):
            for line in out.splitlines():
                if line.startswith("CHAOS_RESULT "):
                    results[i] = json.loads(line[len("CHAOS_RESULT "):])

        for i in range(world):
            if i in hung:
                if i in results:
                    failures.append(
                        f"rank {i} was expected to hang (partition) but "
                        f"completed: {results[i]}")
                continue
            want = expected_exit.get(i, 0)
            got = procs[i].returncode
            if got != want:
                failures.append(
                    f"rank {i}: unexpected exit {got} (wanted {want}); "
                    f"tail: {outs[i][-800:]!r}")
        survivors = [results[i] for i in sorted(results)
                     if i not in hung and expected_exit.get(i, 0) == 0]
        if not survivors:
            failures.append("no surviving rank reported CHAOS_RESULT")
        total = int(os.environ.get("CHAOS_TOTAL_STEPS", "8"))
        if spec.get("check_w", True):
            for r in survivors:
                if r["step"] != total or abs(r["w"] - total) > 1e-4:
                    failures.append(
                        f"lost steps on rank {r['rank']}: "
                        f"step={r['step']} w={r['w']} (want {total})")
        for field in spec.get("require_true", ()):
            for r in survivors:
                if not r.get(field):
                    failures.append(
                        f"rank {r['rank']}: expected {field}=true, "
                        f"got {r.get(field)!r}")
        want_groups = spec.get("require_hier_groups")
        if want_groups is not None:
            for r in survivors:
                if r.get("hier_groups") != want_groups:
                    failures.append(
                        f"rank {r['rank']}: expected the re-formed plan "
                        f"to run {want_groups} groups, got "
                        f"{r.get('hier_groups')!r}")
        retries = sum(r["net_retries_total"] for r in survivors)
        injections = sum(r["chaos_injected_total"] for r in survivors)
        if spec.get("require_retries") and retries <= 0:
            failures.append("expected nonzero horovod_net_retries_total")
        if spec.get("require_injections") and injections <= 0:
            failures.append(
                "expected nonzero horovod_net_chaos_injected_total")
        if spec.get("require_reform") and not any(
                r["generation"] >= 1 for r in survivors):
            failures.append("expected an elastic re-form (generation >= 1)")

        if ckpt_dir and spec.get("ckpt_verify") == "midcommit":
            _verify_ckpt_midcommit(ckpt_dir, total, failures)
        elif ckpt_dir and spec.get("ckpt_verify") == "manifest":
            _verify_ckpt_manifest(ckpt_dir, total, failures)

        if spec.get("require_comms_state"):
            dumps = _collect_dumps(flight_dir, server)
            ledgers = [(d.get("state") or {}).get("comms") for d in dumps]
            ledgers = [c for c in ledgers if isinstance(c, dict)]
            if len(ledgers) < world:
                failures.append(
                    f"only {len(ledgers)}/{world} dumps embedded the "
                    "comms state provider")
            elif not any(
                    ((c.get("lanes") or {}).get("host_ring") or {})
                    .get("bytes_total") for c in ledgers):
                failures.append(
                    "comms ledgers recorded no host_ring traffic")
            elif "=== comms report" not in                     flight_recorder.format_postmortem(dumps):
                failures.append(
                    "postmortem lacks the comms report section")

        if spec.get("require_goodput"):
            # per-survivor ledger invariants (CHAOS_RESULT goodput_*
            # fields), then the cross-rank forensics in the postmortem
            for r in survivors:
                acct = r.get("goodput_accounted")
                if not isinstance(acct, (int, float)) or acct < 0.9:
                    failures.append(
                        f"rank {r['rank']}: goodput ledger accounts "
                        f"{acct!r} of wall-clock, want >= 0.9")
                badput = r.get("goodput_badput") or {}
                if not badput.get("rollback"):
                    failures.append(
                        f"rank {r['rank']}: no rollback badput — the "
                        f"replayed step(s) were counted as productive "
                        f"time ({badput})")
                if not badput.get("elastic_reform"):
                    failures.append(
                        f"rank {r['rank']}: re-form downtime missing "
                        f"from elastic_reform badput ({badput})")
                if not r.get("goodput_replayed"):
                    failures.append(
                        f"rank {r['rank']}: ledger recorded no "
                        "replayed steps")
            dumps = _collect_dumps(flight_dir, server)
            gp_post = flight_recorder.format_postmortem(dumps)
            if "=== goodput report" not in gp_post:
                failures.append(
                    "postmortem lacks the goodput report section")
            elif "costliest incident:" not in gp_post:
                failures.append(
                    "goodput report does not name the costliest "
                    "incident:\n" + gp_post)
            elif "culprit rank" not in gp_post:
                failures.append(
                    "goodput report's costliest incident names no "
                    "culprit rank:\n" + gp_post)

        postmortem = ""
        culprit = spec.get("require_culprit")
        if culprit is not None:
            dumps = _collect_dumps(flight_dir, server)
            postmortem = flight_recorder.format_postmortem(dumps)
            if f"suspected culprit: rank {culprit}" not in postmortem:
                failures.append(
                    f"postmortem does not name rank {culprit}:\n"
                    + postmortem)
        return {
            "scenario": name,
            "ok": not failures,
            "failures": failures,
            "results": [results.get(i) for i in range(world)],
            "exit_codes": [p.returncode for p in procs],
            "net_retries_total": retries,
            "chaos_injected_total": injections,
            "postmortem_tail": postmortem.splitlines()[-12:]
            if postmortem else [],
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
        shutil.rmtree(flight_dir, ignore_errors=True)
        if ckpt_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", help="run a single scenario by name")
    parser.add_argument("--json", help="also write the summary to a file")
    args = parser.parse_args()

    if not native_built():
        print(json.dumps({"ok": False,
                          "error": "native transport not built"}))
        return 1

    names = [args.only] if args.only else list(SCENARIOS)
    for name in names:
        if name not in SCENARIOS:
            print(json.dumps({"ok": False,
                              "error": f"unknown scenario {name!r}"}))
            return 1

    summary = {"ok": True, "scenarios": []}
    for name in names:
        print(f"chaos_matrix: running {name} ...", file=sys.stderr,
              flush=True)
        result = run_scenario(name, SCENARIOS[name])
        summary["scenarios"].append(result)
        if not result["ok"]:
            summary["ok"] = False
        print(f"chaos_matrix: {name}: "
              f"{'ok' if result['ok'] else 'FAILED'}",
              file=sys.stderr, flush=True)

    text = json.dumps(summary, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
