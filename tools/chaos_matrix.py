#!/usr/bin/env python
"""Network-chaos acceptance matrix (ISSUE 8).

Runs the fault-mode × phase matrix as real multiprocess scenarios over
the socket/native transport — the rendezvous HTTP store lives in this
process, standing in for the tpurun launcher — and emits ONE JSON
summary on stdout. Exit status 0 only when every scenario meets its
expectations; any unexpected worker death (or a missed invariant) exits
1.

Scenarios (docs/robustness.md has the failure-model table):

* ``flaky_negotiate``   — ``flaky:0.3`` during negotiate: training
  completes with zero lost steps and nonzero retries.
* ``netdelay_negotiate``— fixed per-op latency: completes, injections
  counted.
* ``kv_outage_reform``  — rank 1 killed at step 3 while the rendezvous
  store answers 503 for 5s starting at the first re-form registration:
  survivors bridge the outage and finish.
* ``partition_collective_timeout`` — a permanent partition of rank 1
  mid-run: survivors trip HOROVOD_COLLECTIVE_TIMEOUT, re-form within
  the deadline, finish, and the merged flight-recorder postmortem names
  the partitioned rank.

Usage: python tools/chaos_matrix.py [--only NAME] [--json PATH]
"""

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from horovod_tpu import flight_recorder  # noqa: E402
from horovod_tpu.run.rendezvous import RendezvousServer  # noqa: E402
from horovod_tpu.runtime.native import native_built  # noqa: E402

WORKER = os.path.join(REPO, "tools", "chaos_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


SCENARIOS = {
    "flaky_negotiate": {
        "world": 2,
        "env": {
            "HOROVOD_FAULT_INJECT": "flaky:0.3:seconds=8",
            # 0.3^k exhaustion over thousands of control rounds needs a
            # deeper per-op attempt budget than the default 4
            "HOROVOD_NET_MAX_RETRIES": "12",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "CHAOS_STEP_SLEEP": "0.2",
        },
        "require_retries": True,
        "timeout": 180,
    },
    "netdelay_negotiate": {
        "world": 2,
        "env": {
            "HOROVOD_FAULT_INJECT": "netdelay:10",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "require_injections": True,
        "timeout": 180,
    },
    "kv_outage_reform": {
        "world": 3,
        "env": {
            "HOROVOD_FAULT_INJECT":
                "kill:rank=1:step=3:code=17;kv_outage:5:on=reform",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
        },
        "expected_exit": {1: 17},
        "require_retries": True,
        "require_reform": True,
        "timeout": 240,
    },
    "partition_collective_timeout": {
        "world": 3,
        "env": {
            "HOROVOD_FAULT_INJECT": "partition:1:600:after=4",
            "HOROVOD_COLLECTIVE_TIMEOUT": "4",
            "HOROVOD_GLOO_TIMEOUT_SECONDS": "8",
            "HOROVOD_ELASTIC_MIN_WORKERS": "2",
            "CHAOS_STEP_SLEEP": "1.0",
        },
        "hung_ranks": [1],
        "require_reform": True,
        "require_culprit": 1,
        "timeout": 240,
    },
}


def _collect_dumps(flight_dir, server):
    """Local flight-rank-*.json files + dumps shipped to the rendezvous
    ``flight`` scope, deduplicated by launch rank (shipped wins — it is
    at least as recent as the file)."""
    by_rank = {}
    for d in flight_recorder.load_dumps(flight_dir):
        by_rank[d.get("launch_rank", d.get("rank"))] = d
    for key in server.live_keys(flight_recorder.RENDEZVOUS_SCOPE):
        raw = server.get(flight_recorder.RENDEZVOUS_SCOPE, key)
        try:
            d = json.loads(raw)
        except (TypeError, ValueError):
            continue
        by_rank[d.get("launch_rank", d.get("rank"))] = d
    return list(by_rank.values())


def run_scenario(name, spec):
    world = spec["world"]
    timeout = spec.get("timeout", 240)
    hung = set(spec.get("hung_ranks", ()))
    expected_exit = dict(spec.get("expected_exit", {}))
    flight_dir = tempfile.mkdtemp(prefix="chaos-flight-")
    server = RendezvousServer(host="127.0.0.1")
    http_port = server.start()
    socket_port = _free_port()
    procs = []
    outs = [""] * world
    failures = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "HOROVOD_RANK": str(rank),
                "HOROVOD_SIZE": str(world),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
                "HOROVOD_RENDEZVOUS_HTTP_ADDR": "127.0.0.1",
                "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_GLOO_TIMEOUT_SECONDS": "5",
                "HOROVOD_FLIGHT_RECORDER_DIR": flight_dir,
                "JAX_PLATFORMS": "cpu",
            })
            env.update(spec.get("env", {}))
            procs.append(subprocess.Popen(
                [sys.executable, WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        # wait for every rank that is expected to terminate on its own;
        # a permanently-partitioned rank blocks forever by design and is
        # reaped after the survivors finish
        deadline = time.monotonic() + timeout
        waiting = {i for i in range(world) if i not in hung}
        while waiting and time.monotonic() < deadline:
            for i in sorted(waiting):
                if procs[i].poll() is not None:
                    waiting.discard(i)
            time.sleep(0.2)
        for i in sorted(waiting):
            failures.append(f"rank {i} did not finish within {timeout}s")
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
                if i not in hung and i in waiting:
                    pass  # already reported as a timeout above
            out, _ = p.communicate(timeout=30)
            outs[i] = out or ""

        results = {}
        for i, out in enumerate(outs):
            for line in out.splitlines():
                if line.startswith("CHAOS_RESULT "):
                    results[i] = json.loads(line[len("CHAOS_RESULT "):])

        for i in range(world):
            if i in hung:
                if i in results:
                    failures.append(
                        f"rank {i} was expected to hang (partition) but "
                        f"completed: {results[i]}")
                continue
            want = expected_exit.get(i, 0)
            got = procs[i].returncode
            if got != want:
                failures.append(
                    f"rank {i}: unexpected exit {got} (wanted {want}); "
                    f"tail: {outs[i][-800:]!r}")
        survivors = [results[i] for i in sorted(results)
                     if i not in hung and expected_exit.get(i, 0) == 0]
        if not survivors:
            failures.append("no surviving rank reported CHAOS_RESULT")
        total = int(os.environ.get("CHAOS_TOTAL_STEPS", "8"))
        for r in survivors:
            if r["step"] != total or abs(r["w"] - total) > 1e-4:
                failures.append(
                    f"lost steps on rank {r['rank']}: step={r['step']} "
                    f"w={r['w']} (want {total})")
        retries = sum(r["net_retries_total"] for r in survivors)
        injections = sum(r["chaos_injected_total"] for r in survivors)
        if spec.get("require_retries") and retries <= 0:
            failures.append("expected nonzero horovod_net_retries_total")
        if spec.get("require_injections") and injections <= 0:
            failures.append(
                "expected nonzero horovod_net_chaos_injected_total")
        if spec.get("require_reform") and not any(
                r["generation"] >= 1 for r in survivors):
            failures.append("expected an elastic re-form (generation >= 1)")

        postmortem = ""
        culprit = spec.get("require_culprit")
        if culprit is not None:
            dumps = _collect_dumps(flight_dir, server)
            postmortem = flight_recorder.format_postmortem(dumps)
            if f"suspected culprit: rank {culprit}" not in postmortem:
                failures.append(
                    f"postmortem does not name rank {culprit}:\n"
                    + postmortem)
        return {
            "scenario": name,
            "ok": not failures,
            "failures": failures,
            "results": [results.get(i) for i in range(world)],
            "exit_codes": [p.returncode for p in procs],
            "net_retries_total": retries,
            "chaos_injected_total": injections,
            "postmortem_tail": postmortem.splitlines()[-12:]
            if postmortem else [],
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
        shutil.rmtree(flight_dir, ignore_errors=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", help="run a single scenario by name")
    parser.add_argument("--json", help="also write the summary to a file")
    args = parser.parse_args()

    if not native_built():
        print(json.dumps({"ok": False,
                          "error": "native transport not built"}))
        return 1

    names = [args.only] if args.only else list(SCENARIOS)
    for name in names:
        if name not in SCENARIOS:
            print(json.dumps({"ok": False,
                              "error": f"unknown scenario {name!r}"}))
            return 1

    summary = {"ok": True, "scenarios": []}
    for name in names:
        print(f"chaos_matrix: running {name} ...", file=sys.stderr,
              flush=True)
        result = run_scenario(name, SCENARIOS[name])
        summary["scenarios"].append(result)
        if not result["ok"]:
            summary["ok"] = False
        print(f"chaos_matrix: {name}: "
              f"{'ok' if result['ok'] else 'FAILED'}",
              file=sys.stderr, flush=True)

    text = json.dumps(summary, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
