"""Worker script for the network-chaos matrix (tools/chaos_matrix.py)
and the multiprocess chaos tests.

Same elastic training loop as tests/elastic_worker.py — one
Average-allreduce of ones per step, so ``w == step`` at every commit
(the zero-lost-steps invariant) — plus:

* ``CHAOS_STEP_SLEEP`` seconds of per-step sleep, so timer-armed faults
  (``partition:...:after=N``) land *inside* the training window instead
  of after an instant CPU run has already finished;
* a machine-readable ``CHAOS_RESULT {json}`` line with the step/weight
  invariants and the resilience counters the matrix asserts on;
* a final flight-recorder dump, so the merged postmortem sees the
  re-form membership events (a failure-time dump predates the re-form).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic, flight_recorder

TOTAL_STEPS = int(os.environ.get("CHAOS_TOTAL_STEPS", "8"))
STEP_SLEEP = float(os.environ.get("CHAOS_STEP_SLEEP", "0"))
# integrity skip-step mode: watch the reduced "gradient" with the spike
# guard and retry a flagged step without applying or committing it —
# the nan chaos scenario proves a poisoned batch costs one retried step,
# not a corrupted w (guard lives outside train: replays must not reset
# its EWMA statistics)
GUARD = None
if os.environ.get("CHAOS_INTEGRITY_GUARD") == "1":
    from horovod_tpu.integrity import guards as _guards

    GUARD = _guards.StepGuard(name="chaos_grad")


@elastic.run
def train(state):
    while state.step < TOTAL_STEPS:
        grad = hvd.allreduce(np.ones(4, np.float32), average=True,
                             name="chaos_grad")
        if GUARD is not None and not GUARD.observe(
                float(np.asarray(grad)[0])):
            continue  # skip: every rank saw the same reduced value
        state.params["w"] = state.params["w"] + np.asarray(grad)
        state.step += 1
        state.commit()
        if STEP_SLEEP:
            time.sleep(STEP_SLEEP)
    return state


def _metric_total(snap, name):
    fam = snap.get(name, {})
    return float(sum(row.get("value", 0.0)
                     for row in fam.get("values", ())))


def main() -> int:
    hvd.init()
    state = elastic.ArrayState(
        params={"w": np.zeros(4, np.float32)}, optimizer=None, step=0)
    train(state)

    w = float(state.params["w"][0])
    snap = hvd.metrics()
    result = {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "step": state.step,
        "w": w,
        "generation": elastic.restarts(),
        "net_retries_total": _metric_total(
            snap, "horovod_net_retries_total"),
        "net_gave_up_total": _metric_total(
            snap, "horovod_net_gave_up_total"),
        "chaos_injected_total": _metric_total(
            snap, "horovod_net_chaos_injected_total"),
        "integrity_checks": _metric_total(
            snap, "horovod_integrity_checks_total"),
        "integrity_violations": _metric_total(
            snap, "horovod_integrity_violations_total"),
        "rollbacks": _metric_total(
            snap, "horovod_integrity_rollbacks_total"),
        "skipped_steps": _metric_total(
            snap, "horovod_integrity_skipped_steps_total"),
    }
    # hierarchy-plan visibility (ISSUE 18 chaos cell): after an elastic
    # re-form the executor must have recomputed the groups for the NEW
    # world size — report what the survivors actually ended up running.
    # Safe here: the explicit-group-size plan is wire-free and the cycle
    # thread is idle after train().
    try:
        from horovod_tpu.core import state as state_mod

        plan = state_mod.global_state().runtime.executor._hierarchy_plan()
        result["hier_enabled"] = plan is not None
        if plan is not None:
            result["hier_groups"] = plan.num_groups
            result["hier_group_size"] = plan.group_size
    except Exception:
        result["hier_enabled"] = False
    # goodput ledger (goodput_attribution chaos cell): the matrix asserts
    # that survivors account their wall-clock — replayed steps charged to
    # the rollback incident, re-form downtime in elastic_reform — so the
    # ledger fields ride the result line like the hier_* fields above
    try:
        from horovod_tpu import goodput

        led = goodput.tracker().ledger()
        result["goodput_fraction"] = led["goodput_fraction"]
        result["goodput_accounted"] = led["accounted_fraction"]
        result["goodput_badput"] = led["badput_seconds"]
        result["goodput_replayed"] = led["steps_replayed"]
        result["goodput_incidents"] = led["incident_counts"]
    except Exception:
        pass
    try:  # the postmortem needs post-reform events (elastic_reform)
        flight_recorder.dump_debug_state(reason="chaos_run_complete")
    except Exception:
        pass
    print("CHAOS_RESULT " + json.dumps(result), flush=True)
    ok = state.step == TOTAL_STEPS and abs(w - TOTAL_STEPS) <= 1e-4
    hvd.shutdown()
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
