#!/usr/bin/env python
"""Cross-check the ``HOROVOD_*`` environment-variable contract.

Every ``HOROVOD_*`` knob referenced by the package must be documented in
the docs tree (``docs/*.md`` + ``README.md``), every knob the docs
promise must still exist somewhere in the code — docs and code drift in
opposite directions and both drifts strand users (an undocumented knob is
undiscoverable; a documented-but-removed knob silently does nothing) —
and every knob must be *registered* in ``horovod_tpu/utils/env.py``,
either as a named constant parsed into ``Config`` or in the
``ENV_DIRECT_KNOBS`` catalog of point-of-use reads, so there is exactly
one place to see the full contract.

Run directly (exits nonzero on drift, listing the offenders)::

    python tools/check_env_knobs.py

or via the tier-1 suite (tests/test_env_knobs.py). Docs may document a
family with a trailing-underscore wildcard (``HOROVOD_STALL_CHECK_*``),
which covers every code var sharing the prefix.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Iterable, Set, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
# the (?!\[) rejects prose like "HOROVOD_WITH[OUT]_*" naming a knob family
TOKEN_RE = re.compile(r"\bHOROVOD_[A-Z0-9_]+\b(?!\[)")

# where knobs are *referenced* (package + build + launcher glue)
CODE_GLOBS = (
    ("horovod_tpu", "**/*.py"),
    ("cpp", "**/*.cc"),
    ("bin", "**/*"),
    (".", "setup.py"),
)
# where knobs are *documented*
DOC_GLOBS = (
    ("docs", "**/*.md"),
    (".", "README.md"),
)


def _scan(root: Path, globs: Iterable[Tuple[str, str]]) -> Set[str]:
    tokens: Set[str] = set()
    for base, pattern in globs:
        for path in sorted((root / base).glob(pattern)):
            if not path.is_file():
                continue
            try:
                text = path.read_text(errors="replace")
            except OSError:
                continue
            tokens.update(TOKEN_RE.findall(text))
    return tokens


def _drop_fragments(tokens: Set[str]) -> Set[str]:
    """Drop wrapped-string-literal fragments: a token ending in ``_`` that
    is a proper prefix of another collected token is half of a split
    literal, not a real knob."""
    return {t for t in tokens
            if not (t.endswith("_")
                    and any(o != t and o.startswith(t) for o in tokens))}


def collect_code_vars(root: Path = REPO_ROOT) -> Set[str]:
    return _drop_fragments(_scan(root, CODE_GLOBS))


def collect_doc_vars(root: Path = REPO_ROOT) -> Tuple[Set[str], Set[str]]:
    """Returns (exact names, wildcard prefixes). A docs token ending in
    ``_`` (e.g. from ``HOROVOD_STALL_CHECK_*``) is a wildcard prefix."""
    tokens = _scan(root, DOC_GLOBS)
    prefixes = {t for t in tokens if t.endswith("_")}
    return tokens - prefixes, prefixes


# the single registration point: every knob must appear here — as a name
# constant feeding Config.from_env, or in the ENV_DIRECT_KNOBS catalog
REGISTRY_FILE = ("horovod_tpu", "utils/env.py")


def collect_registered_vars(root: Path = REPO_ROOT) -> Set[str]:
    return _drop_fragments(_scan(root, (REGISTRY_FILE,)))


def check(root: Path = REPO_ROOT) -> Tuple[Set[str], Set[str], Set[str]]:
    """Returns (undocumented code vars, stale docs vars, unregistered
    code vars — referenced somewhere but absent from utils/env.py)."""
    code = collect_code_vars(root)
    exact, prefixes = collect_doc_vars(root)
    registered = collect_registered_vars(root)
    undocumented = {
        v for v in code
        if v not in exact and not any(v.startswith(p) for p in prefixes)}
    stale = {
        v for v in exact
        if v not in code and not any(c.startswith(v) for c in code)}
    unregistered = code - registered
    return undocumented, stale, unregistered


def main(argv: list = ()) -> int:
    root = Path(argv[0]) if argv else REPO_ROOT
    undocumented, stale, unregistered = check(root)
    for v in sorted(undocumented):
        print(f"UNDOCUMENTED: {v} is referenced in code but appears "
              f"nowhere under docs/ or README.md", file=sys.stderr)
    for v in sorted(stale):
        print(f"STALE: {v} is documented but no longer referenced "
              f"anywhere in code", file=sys.stderr)
    for v in sorted(unregistered):
        print(f"UNREGISTERED: {v} is referenced in code but not "
              f"registered in horovod_tpu/utils/env.py (add a Config "
              f"field or an ENV_DIRECT_KNOBS entry)", file=sys.stderr)
    if undocumented or stale or unregistered:
        return 1
    print(f"env knob contract ok "
          f"({len(collect_code_vars(root))} vars cross-checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
