"""Worker for the sharded-AdamW checkpoint chaos scenario
(``ckpt_reform_sharded_adamw`` in tools/chaos_matrix.py).

ZeRO-1 ``hvd.sharded_adamw`` training where every parameter element
starts equal and every gradient element is 1.0 — so every REAL element
of the flat fp32 master/mu/nu buffers stays exactly equal across the
whole (sharded) buffer at every step. That uniformity is the oracle for
the neighbor-replica restore: when rank 1 is killed and the survivors
re-form, the dead rank's moment segments must come back from its left
neighbor's replica (PR-9), not as zeros (the PR-5 ``zero.resync`` data
loss). Zero-filled segments would evolve differently from the
surviving segments for the rest of the run, so the final check — all
real mu/nu elements nonzero AND identical across every surviving
shard — distinguishes a replica restore from a zero-fill, not just
from a crash.

Emits ``CHAOS_RESULT {json}`` with boolean fields the matrix asserts
via ``require_true``: ``steps_ok``, ``moments_nonzero``,
``moments_uniform``, ``replica_restored``.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic, flight_recorder

TOTAL_STEPS = int(os.environ.get("CHAOS_TOTAL_STEPS", "8"))
STEP_SLEEP = float(os.environ.get("CHAOS_STEP_SLEEP", "0"))
# not divisible by 2 or 3: both the pre- and post-reform shard layouts
# carry zero-padding, so the real-vs-padding masking is exercised
N = 37

SOPT = None


@elastic.run
def train(state):
    import jax.numpy as jnp

    while state.step < TOTAL_STEPS:
        grads = {"w": jnp.ones((N,), jnp.float32)}
        state.params, state.optimizer = SOPT.apply(
            state.params, state.optimizer, grads)
        state.step += 1
        state.commit()
        if STEP_SLEEP:
            time.sleep(STEP_SLEEP)
    return state


def _real_moments(opt_state):
    """Per-component REAL (non-padding) elements of this rank's moment
    shards: ``{"mu": array, "nu": array}``. spec.rank * shard_elems is
    this shard's offset in the flat buffer; elements whose global index
    is >= n are padding. mu and nu hold different values by nature, so
    uniformity is only meaningful per component."""
    from horovod_tpu.parallel import zero

    export = zero.export_shard_arrays(opt_state)
    spec = opt_state.spec
    out = {}
    for comp in ("mu", "nu"):
        parts = []
        for g, arr in zip(spec.groups, export[comp]):
            arr = np.asarray(arr).reshape(-1)
            offset = spec.rank * g.shard_elems
            parts.append(arr[:max(0, min(g.n - offset, arr.size))])
        out[comp] = (np.concatenate(parts) if parts
                     else np.zeros(0, np.float32))
    return out


def _metric_total(snap, name):
    fam = snap.get(name, {})
    return float(sum(row.get("value", 0.0)
                     for row in fam.get("values", ())))


def main() -> int:
    global SOPT
    import jax.numpy as jnp

    hvd.init()
    SOPT = hvd.sharded_adamw(0.1)
    params = {"w": jnp.full((N,), 0.5, jnp.float32)}
    state = elastic.ArrayState(
        params=params, optimizer=SOPT.init(params), step=0)
    train(state)
    state.checkpoint_wait()

    moments = _real_moments(state.optimizer)
    moments_nonzero = bool(all(
        arr.size == 0 or np.all(np.abs(arr) > 0)
        for arr in moments.values()) and any(
        arr.size for arr in moments.values()))
    # per component: locally uniform, and the uniform value agrees
    # across every surviving shard (min/max allgather) — a zero-filled
    # replica would break one or the other
    moments_uniform = True
    for comp in ("mu", "nu"):
        arr = moments[comp]
        local = np.array([arr.min() if arr.size else np.nan,
                          arr.max() if arr.size else np.nan], np.float64)
        gathered = np.asarray(hvd.allgather(
            local, name=f"ckpt_chaos_mm_{comp}"))
        vals = gathered[np.isfinite(gathered)]
        if vals.size and float(vals.max() - vals.min()) != 0.0:
            moments_uniform = False

    snap = hvd.metrics()
    replica_restores = _metric_total(
        snap, "horovod_ckpt_replica_restores_total")
    result = {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "step": state.step,
        "generation": elastic.restarts(),
        "steps_ok": state.step == TOTAL_STEPS,
        "moments_nonzero": moments_nonzero,
        "moments_uniform": moments_uniform,
        "replica_restored": replica_restores > 0,
        "replica_restores_total": replica_restores,
        "net_retries_total": _metric_total(
            snap, "horovod_net_retries_total"),
        "net_gave_up_total": _metric_total(
            snap, "horovod_net_gave_up_total"),
        "chaos_injected_total": _metric_total(
            snap, "horovod_net_chaos_injected_total"),
    }
    try:  # the postmortem needs post-reform events
        flight_recorder.dump_debug_state(reason="chaos_run_complete")
    except Exception:
        pass
    print("CHAOS_RESULT " + json.dumps(result), flush=True)
    ok = (result["steps_ok"] and moments_nonzero and moments_uniform)
    hvd.shutdown()
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
