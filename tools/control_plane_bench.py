#!/usr/bin/env python
"""Control-plane benchmark — the framework's Horovod-headline numbers.

VERDICT r2 ask 4: no committed number demonstrated the control plane's
actual value prop (negotiation amortization via the response cache,
tensor fusion, autotune). This harness spawns a real multi-process world
over the native wire (the launcher env contract, like
tests/test_multiprocess.py) and measures on the host:

  * slow-path negotiation latency: per-op wall time when every op uses a
    FRESH name (full gather/construct/fuse/bcast negotiation each cycle;
    reference: the ComputeResponseList slow path, operations.cc:556-698)
  * cache fast path: per-op wall time for steady-state repeated names
    (bit-sync only; reference: response_cache.cc)
  * fusion: throughput (bytes/us) pushing K small tensors per step with
    the fusion buffer on vs off (reference: docs/tensor-fusion.rst:9-17)
  * autotune: the same small-tensor workload with HOROVOD_AUTOTUNE=1,
    before (first sample window) vs after (post-warmup) scores
    (reference: parameter_manager.cc:142-176 bytes/us scoring)

Run:  python tools/control_plane_bench.py [--np 4]
Emits one JSON object on stdout (also written per-metric lines by
``bench.py --control-plane``'s caller).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SMALL = 1024          # elements per small tensor (4 KiB fp32)
N_TENSORS = 16        # tensors per fusion step
STEPS = 15            # timed steps per phase (1-core CI boxes are slow)
WARMUP = 3
# --fast (the bench.py no-flag sweep): fewer steps, no autotune launch.
# The lines bench.py reports (ctrl bytes/op, ring steps/op) are protocol
# counters, but ops-per-cycle batching depends on scheduler timing, so
# short windows amortize fixed per-window costs less (measured: 5 steps
# reads amortization 1.94x vs 2.44x at 15) — 10 steps keeps the drift
# small while cutting the 5.5-min full protocol (a third of the r4
# driver window) to ~2 min.
FAST_STEPS = 10
FAST_WARMUP = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def worker() -> None:
    sys.path.insert(0, REPO)
    import horovod_tpu as hvd
    from horovod_tpu.core import state

    hvd.init()
    rank = hvd.rank()
    results = {}
    arrays = [np.ones(SMALL, np.float32) for _ in range(N_TENSORS)]
    fast = os.environ.get("CPB_FAST") == "1"
    steps, warmup = (FAST_STEPS, FAST_WARMUP) if fast else (STEPS, WARMUP)

    # Bursts of N_TENSORS async ops per step, synchronized together.
    # Wall time on a shared-core CI box measures the scheduler more than
    # the protocol, so alongside it each phase records two DETERMINISTIC
    # protocol counters from the native transport: control-plane bytes
    # sent (negotiation gathers/bcasts + cache-bit syncs) and ring-kernel
    # steps (fusion's dispatch count) — box-independent evidence of
    # negotiation amortization and fusion.
    def burst_steps(label, fresh_names):
        uid = [0]

        def one_step():
            handles = []
            for i, a in enumerate(arrays):
                if fresh_names:
                    uid[0] += 1
                    name = f"{label}/fresh.{uid[0]}"
                else:
                    name = f"{label}/t{i}"
                handles.append(hvd.allreduce_async(a, name=name))
            for h in handles:
                hvd.synchronize(h)

        for _ in range(warmup):
            one_step()
        hvd.allreduce(np.zeros(1, np.float32), name=f"{label}/sync")
        # the runtime (and its transport) exists only after the first op
        net = state.global_state().runtime.controller.net
        ctrl0, ex0 = net.ctrl_bytes_sent(), net.exchange_calls()
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        dt = time.perf_counter() - t0
        n_ops = steps * N_TENSORS
        results[label] = {
            "s_per_op": dt / n_ops,
            "ctrl_bytes_per_op": (net.ctrl_bytes_sent() - ctrl0) / n_ops,
            "exchanges_per_op": (net.exchange_calls() - ex0) / n_ops,
        }

    # 1. slow path: fresh name every op -> full negotiation
    #    (gather request lists / construct / fuse / bcast every cycle)
    burst_steps("slow", fresh_names=True)
    # 2. fast path: steady names -> per-cycle fixed-width cache-bit sync
    burst_steps("fast", fresh_names=False)

    # the coordinator pays the bcast fan-out; report ITS counters (the
    # worst-cased control plane), so gather from rank 0
    hvd.shutdown()
    if rank == 0:
        print("RESULTS " + json.dumps(results), flush=True)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def launch(world: int, extra_env: dict, timeout: float = 300.0):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_CONTROLLER": "socket",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
        })
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            if p.returncode != 0:
                raise RuntimeError(
                    f"worker failed rc={p.returncode}:\n{out}")
    finally:
        # a timed-out or failed world must not leave orphans wedged in
        # the rendezvous sockets for the next launch() to hang against
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULTS "):
                return json.loads(line[len("RESULTS "):])
    raise RuntimeError("no RESULTS line from rank 0:\n" + "\n".join(outs))


def main(world: int, fast: bool = False) -> dict:
    fast_env = {"CPB_FAST": "1"} if fast else {}
    # default config: fusion on (64 MB buffer), cache on
    base = launch(world, dict(fast_env))
    # fusion off: zero-byte buffer -> every tensor negotiated alone
    nofuse = launch(world, {"HOROVOD_FUSION_THRESHOLD": "0", **fast_env})
    # autotune enabled over the same workload (it sweeps cycle time /
    # fusion threshold; steady state should match or beat the default).
    # Skipped in --fast: its only output is a wall-clock field the sweep
    # does not report.
    tuned = None if fast else launch(world, {
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "2",
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "10",
    })

    out = {
        "world": world,
        # deterministic protocol metrics (box-independent)
        "ctrl_bytes_per_op_slow_path": round(
            base["slow"]["ctrl_bytes_per_op"], 1),
        "ctrl_bytes_per_op_fast_path": round(
            base["fast"]["ctrl_bytes_per_op"], 1),
        "negotiation_byte_amortization_x": round(
            base["slow"]["ctrl_bytes_per_op"]
            / max(base["fast"]["ctrl_bytes_per_op"], 1e-9), 2),
        "ring_steps_per_op_fused": round(
            base["fast"]["exchanges_per_op"], 3),
        "ring_steps_per_op_unfused": round(
            nofuse["fast"]["exchanges_per_op"], 3),
        "fusion_dispatch_reduction_x": round(
            nofuse["fast"]["exchanges_per_op"]
            / max(base["fast"]["exchanges_per_op"], 1e-9), 2),
        # wall-clock (scheduler-bound on shared-core CI boxes; meaningful
        # on real multi-host deployments)
        "slow_path_us_per_op": round(base["slow"]["s_per_op"] * 1e6, 1),
        "fast_path_us_per_op": round(base["fast"]["s_per_op"] * 1e6, 1),
        "unfused_us_per_op": round(nofuse["fast"]["s_per_op"] * 1e6, 1),
    }
    if tuned is not None:
        out["autotuned_us_per_op"] = round(
            tuned["fast"]["s_per_op"] * 1e6, 1)
    return out


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--np", type=int, default=4)
    parser.add_argument("--fast", action="store_true",
                        help="fewer steps, no autotune launch; the "
                             "deterministic counter metrics are "
                             "unchanged (see header comment)")
    cli = parser.parse_args()
    if cli.worker:
        worker()
    else:
        print(json.dumps(main(cli.np, fast=cli.fast)), flush=True)
