#!/usr/bin/env python
"""Independent ResNet-50 control run — NO framework code.

VERDICT r2 asked for an external control on the "15% MFU is
XLA-structural" claim: an independent ResNet-50 train step that does NOT
go through horovod_tpu (no flax, no optax, no framework imports — every
layer, the batch-norm, and the SGD-momentum update are hand-rolled on
raw jax/lax), same batch/dtype/layout/protocol as bench.py. If this
lands at ~the same img/s, the framework's data path is exonerated on
silicon; if it lands higher, the framework has a bug to find.

Protocol identical to bench.py: NHWC, bf16 compute / f32 params+stats,
batch 128, 224x224, one compiled lax.scan of 20 steps per round, scalar
readback per round, mean over 10 timed rounds. Prints one JSON line.
"""

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BATCH = 128
IMAGE = 224
STEPS_PER_ROUND = 20
WARMUP_ROUNDS = 1
TIMED_ROUNDS = 10
DTYPE = jnp.bfloat16

STAGES = [3, 4, 6, 3]  # ResNet-50 bottleneck counts
FILTERS = [64, 128, 256, 512]


# ---------------------------------------------------------------------------
# layers (hand-rolled)
# ---------------------------------------------------------------------------

def conv(x, w, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x.astype(DTYPE), w.astype(DTYPE), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn_train(x, p, s):
    """Batch norm, training mode: f32 batch stats over (N,H,W), bf16
    apply, running-stat EMA update (momentum 0.9) — the same traffic
    pattern as any standard BN implementation."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    var = jnp.var(xf, axis=(0, 1, 2))
    inv = lax.rsqrt(var + 1e-5) * p["scale"]
    y = (xf - mean) * inv + p["bias"]
    new_s = {"mean": 0.9 * s["mean"] + 0.1 * mean,
             "var": 0.9 * s["var"] + 0.1 * var}
    return y.astype(DTYPE), new_s


def max_pool(x, window=3, stride=2):
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        (1, window, window, 1), (1, stride, stride, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])


# ---------------------------------------------------------------------------
# parameter construction
# ---------------------------------------------------------------------------

def _conv_p(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * np.sqrt(2.0 / fan_in))


def _bn_p(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _bn_s(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def build_params(key):
    keys = iter(jax.random.split(key, 200))
    params = {"conv_init": _conv_p(next(keys), 7, 7, 3, 64),
              "bn_init": _bn_p(64)}
    stats = {"bn_init": _bn_s(64)}
    cin = 64
    for i, n_blocks in enumerate(STAGES):
        f = FILTERS[i]
        for j in range(n_blocks):
            name = f"s{i}b{j}"
            block = {
                "conv1": _conv_p(next(keys), 1, 1, cin, f),
                "bn1": _bn_p(f),
                "conv2": _conv_p(next(keys), 3, 3, f, f),
                "bn2": _bn_p(f),
                "conv3": _conv_p(next(keys), 1, 1, f, f * 4),
                "bn3": _bn_p(f * 4),
            }
            bstat = {"bn1": _bn_s(f), "bn2": _bn_s(f), "bn3": _bn_s(f * 4)}
            if j == 0:  # projection shortcut on every first block
                block["conv_proj"] = _conv_p(next(keys), 1, 1, cin, f * 4)
                block["bn_proj"] = _bn_p(f * 4)
                bstat["bn_proj"] = _bn_s(f * 4)
            params[name] = block
            stats[name] = bstat
            cin = f * 4
    params["dense_w"] = (jax.random.normal(next(keys), (2048, 1000),
                                           jnp.float32) * 0.01)
    params["dense_b"] = jnp.zeros((1000,), jnp.float32)
    return params, stats


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def bottleneck(x, p, s, stride):
    y, s1 = bn_train(conv(x, p["conv1"]), p["bn1"], s["bn1"])
    y = jax.nn.relu(y)
    y, s2 = bn_train(conv(y, p["conv2"], stride), p["bn2"], s["bn2"])
    y = jax.nn.relu(y)
    y, s3 = bn_train(conv(y, p["conv3"]), p["bn3"], s["bn3"])
    new_s = {"bn1": s1, "bn2": s2, "bn3": s3}
    if "conv_proj" in p:
        res, sp = bn_train(conv(x, p["conv_proj"], stride), p["bn_proj"],
                           s["bn_proj"])
        new_s["bn_proj"] = sp
    else:
        res = x
    return jax.nn.relu(res + y), new_s


def forward(params, stats, images):
    x = conv(images, params["conv_init"], 2)
    x, s0 = bn_train(x, params["bn_init"], stats["bn_init"])
    new_stats = {"bn_init": s0}
    x = jax.nn.relu(x)
    x = max_pool(x)
    for i, n_blocks in enumerate(STAGES):
        for j in range(n_blocks):
            name = f"s{i}b{j}"
            stride = 2 if (i > 0 and j == 0) else 1
            x, ns = bottleneck(x, params[name], stats[name], stride)
            new_stats[name] = ns
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["dense_w"] + params["dense_b"]
    return logits, new_stats


def loss_fn(params, stats, images, labels):
    logits, new_stats = forward(params, stats, images)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    return loss, new_stats


# ---------------------------------------------------------------------------
# hand-rolled SGD momentum + the scanned round
# ---------------------------------------------------------------------------

@jax.jit
def train_round(params, stats, momentum, images, labels):
    def step(carry, _):
        params, stats, momentum = carry
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, stats, images, labels)
        momentum = jax.tree_util.tree_map(
            lambda m, g: 0.9 * m + g, momentum, grads)
        params = jax.tree_util.tree_map(
            lambda p, m: p - 0.01 * m, params, momentum)
        return (params, new_stats, momentum), loss

    (params, stats, momentum), losses = lax.scan(
        step, (params, stats, momentum), None, length=STEPS_PER_ROUND)
    return params, stats, momentum, losses[-1]


def main():
    print(f"devices: {jax.devices()}", file=sys.stderr, flush=True)
    params, stats = build_params(jax.random.PRNGKey(0))
    momentum = jax.tree_util.tree_map(jnp.zeros_like, params)
    rng = np.random.RandomState(0)
    images = jnp.asarray(
        rng.uniform(-1, 1, (BATCH, IMAGE, IMAGE, 3)).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)).astype(np.int32))

    t0 = time.perf_counter()
    for _ in range(WARMUP_ROUNDS):
        params, stats, momentum, loss = train_round(
            params, stats, momentum, images, labels)
    jax.block_until_ready(loss)
    print(f"warmup {time.perf_counter() - t0:.1f}s loss={float(loss):.3f}",
          file=sys.stderr, flush=True)

    rates = []
    for r in range(TIMED_ROUNDS):
        t0 = time.perf_counter()
        params, stats, momentum, loss = train_round(
            params, stats, momentum, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rates.append(BATCH * STEPS_PER_ROUND / dt)
        print(f"round {r}: {rates[-1]:.1f} img/s", file=sys.stderr,
              flush=True)

    print(json.dumps({
        "metric": "images/sec/chip (ResNet-50 CONTROL, no framework)",
        "value": round(float(np.mean(rates)), 2),
        "unit": "images/sec/chip",
    }), flush=True)


if __name__ == "__main__":
    main()
