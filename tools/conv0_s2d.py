#!/usr/bin/env python
"""Space-to-depth transform for ResNet's input conv — worth it on v5e?

The classic TPU MLPerf trick: a 7x7 stride-2 conv on (224,224,3) puts 3
channels on a 128-lane MXU. Reparametrize EXACTLY: 2x2 space-to-depth
the input to (112,112,12) and fold the 7x7/2 kernel into a 4x4/1 kernel
over 12 channels with asymmetric [(2,1),(2,1)] padding — identical
output, 4x the contraction depth per MXU pass.

Derivation: o[i,j,k] = sum_{a=-3..3, c} x[2i+a, 2j+b, c] W[a+3,b+3,c,k].
With 2i+a = 2(i+t-2)+u where a = 2(t-2)+u, u in {0,1}, t in [0,4):
o = conv1(S2D(x), W')[i,j,k] with W'[t_h,t_w, c+3*(2*u_h+u_w), k] =
W[2*t_h-4+u_h+3, 2*t_w-4+u_w+3, c, k] (zero where out of range).

Measures both forms isolated (salted slope protocol) and checks
numerical equality. If the win is real, the model grows a
use_space_to_depth flag.
"""

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BATCH = 128
ITERS_SHORT, ITERS_LONG, ROUNDS = 50, 200, 6
FLOPS = 2 * BATCH * 112 * 112 * 49 * 3 * 64  # identical both ways


def s2d(x):
    """2x2 space-to-depth, NHWC: (N,H,W,C) -> (N,H/2,W/2,4C) with the
    channel order c + C*(2*u_h + u_w)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # n, h/2, w/2, uh, uw, c
    return x.reshape(n, h // 2, w // 2, 4 * c)


def fold_kernel(w7):
    """(7,7,3,64) stride-2 kernel -> (4,4,12,64) stride-1 kernel over
    the s2d channel order (c + 3*(2*u_h + u_w))."""
    w4 = np.zeros((4, 4, 12, 64), w7.dtype)
    for th in range(4):
        for uh in range(2):
            ah = 2 * th - 4 + uh + 3
            if not 0 <= ah < 7:
                continue
            for tw in range(4):
                for uw in range(2):
                    aw = 2 * tw - 4 + uw + 3
                    if not 0 <= aw < 7:
                        continue
                    w4[th, tw, 3 * (2 * uh + uw):3 * (2 * uh + uw) + 3] \
                        = w7[ah, aw]
    return w4


def conv0_direct(x, w7):
    return lax.conv_general_dilated(
        x, w7, (2, 2), [(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv0_s2d(y, w4):
    return lax.conv_general_dilated(
        y, w4, (1, 1), [(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@partial(jax.jit, static_argnames="iters")
def chain_direct(x, w7, salt, iters):
    x = x + salt.astype(x.dtype)

    def body(x, _):
        y = conv0_direct(x, w7)
        return x + 1e-6 * jnp.mean(y).astype(x.dtype), ()

    x, _ = lax.scan(body, x, None, length=iters)
    return jnp.sum(x[0, 0, 0, :].astype(jnp.float32))


@partial(jax.jit, static_argnames="iters")
def chain_s2d(x, w4, salt, iters):
    x = x + salt.astype(x.dtype)

    def body(x, _):
        y = conv0_s2d(s2d(x), w4)  # includes the s2d data movement
        return x + 1e-6 * jnp.mean(y).astype(x.dtype), ()

    x, _ = lax.scan(body, x, None, length=iters)
    return jnp.sum(x[0, 0, 0, :].astype(jnp.float32))


_salt = [0]


def fresh():
    _salt[0] += 1
    return jnp.float32(_salt[0] * 1e-7)


def slope(fn, *args):
    for it in (ITERS_SHORT, ITERS_LONG):
        float(fn(*args, fresh(), iters=it))
    out = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        float(fn(*args, fresh(), iters=ITERS_SHORT))
        t1 = time.perf_counter()
        float(fn(*args, fresh(), iters=ITERS_LONG))
        t2 = time.perf_counter()
        out.append(((t2 - t1) - (t1 - t0)) / (ITERS_LONG - ITERS_SHORT))
    return float(np.median(out))


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (BATCH, 224, 224, 3)),
                    dtype=jnp.bfloat16)
    w7 = rng.uniform(-0.1, 0.1, (7, 7, 3, 64)).astype(np.float32)
    w4 = jnp.asarray(fold_kernel(w7), jnp.bfloat16)
    w7 = jnp.asarray(w7, jnp.bfloat16)

    # exactness check
    a = np.asarray(conv0_direct(x, w7), np.float32)
    b = np.asarray(conv0_s2d(s2d(x), w4), np.float32)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)
    print("numerics ok", file=sys.stderr, flush=True)

    t_direct = slope(chain_direct, x, w7)
    t_s2d = slope(chain_s2d, x, w4)
    print(json.dumps({
        "direct_us": round(t_direct * 1e6, 1),
        "s2d_us": round(t_s2d * 1e6, 1),
        "direct_mfu": round(FLOPS / t_direct / 197e12, 4),
        "s2d_mfu": round(FLOPS / t_s2d / 197e12, 4),
        "speedup_x": round(t_direct / t_s2d, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
