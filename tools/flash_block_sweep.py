#!/usr/bin/env python
"""Flash-attention block-size sweep at the bench shapes (round 4).

The kernel's default blocks (fwd q512/k1024, bwd 1024²) were tuned on
head_dim 128; the transformer headlines run head_dim 64 (BERT-Large
B8 H16 S512 non-causal, GPT-2 B16 H12 S1024 causal). Causal shapes are
the interesting case: the kernel skips k-blocks entirely in a q-block's
future (flash_attention.py `interior` predicate), so SMALLER k-blocks
skip more masked work — at seq 1024 a single 1024-wide k block can
never be skipped.

Protocol: the house slope timing (salted chains, t(2N)-t(N)) on the
isolated 24-layer (BERT) / 12-layer (GPT-2) attention stack, fwd and
fwd+bwd, per block config. One config per invocation (--shape, --blocks
"bq,bk,bbq,bbk") so a tunnel hiccup loses one point; drive from a shell
loop.
"""

import argparse
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402

SHAPES = {
    # label: (batch, heads, seq, head_dim, layers, causal)
    "bert-large": (8, 16, 512, 64, 24, False),
    "gpt2": (16, 12, 1024, 64, 12, True),
}
ITERS = 10
ROUNDS = 6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", choices=sorted(SHAPES), required=True)
    ap.add_argument("--blocks", required=True,
                    help="bq,bk,bwd_bq,bwd_bk")
    ap.add_argument("--grad", action="store_true",
                    help="time fwd+bwd instead of fwd")
    args = ap.parse_args()
    b, h, s, d, layers, causal = SHAPES[args.shape]
    bq, bk, bbq, bbk = (int(x) for x in args.blocks.split(","))

    rng = np.random.RandomState(0)
    q0 = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    k0 = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    v0 = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)

    attn = partial(flash_attention, causal=causal, block_q=bq, block_k=bk,
                   bwd_block_q=bbq, bwd_block_k=bbk)

    @partial(jax.jit, static_argnames="iters")
    def fwd_chain(q, k, v, salt, iters):
        def body(q_c, _):
            x = q_c
            for _ in range(layers):
                x = attn(x, k, v)
            out = jnp.mean(x[:, 0, 0, :].astype(jnp.float32))
            return q_c + (1e-6 * out + salt).astype(q_c.dtype), out

        _, outs = jax.lax.scan(body, q, None, length=iters)
        return outs[-1]

    @partial(jax.jit, static_argnames="iters")
    def grad_chain(q, k, v, salt, iters):
        def attn_loss(q_c):
            x = q_c
            for _ in range(layers):
                x = attn(x, k, v)
            return jnp.mean(x.astype(jnp.float32))

        def body(q_c, _):
            out, g = jax.value_and_grad(attn_loss)(q_c)
            return (q_c - 1e-6 * g.astype(q_c.dtype)
                    + jnp.asarray(salt * 1e-12, q_c.dtype)), out

        _, outs = jax.lax.scan(body, q, None, length=iters)
        return outs[-1]

    fn = grad_chain if args.grad else fwd_chain
    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    for iters in (ITERS, 2 * ITERS):
        float(fn(q0, k0, v0, fresh_salt(), iters=iters))
    slopes = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        float(fn(q0, k0, v0, fresh_salt(), iters=ITERS))
        t1 = time.perf_counter()
        float(fn(q0, k0, v0, fresh_salt(), iters=2 * ITERS))
        t2 = time.perf_counter()
        slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
    ms = float(np.median(slopes)) * 1e3
    print(json.dumps({"shape": args.shape, "blocks": args.blocks,
                      "phase": "fwd+bwd" if args.grad else "fwd",
                      f"{layers}x_ms": round(ms, 2)}), flush=True)


if __name__ == "__main__":
    main()
