#!/usr/bin/env python
"""Attack plan for the flash kernel's ~24%-MFU attention term (VERDICT
r4/r5 ask: the last double-digit perf item).

The r4 decomposition (docs/perf_experiments.md) pinned BERT-Large's
attention at ~24% MFU vs the dense trunk's ~65%, and excluded the MXU
side (bf16 operands: flat; block sweep: defaults stand) — leaving the
VPU softmax/layout term at head_dim 64. This probe measures, with the
same slope protocol as tools/bert_decompose.py (dispatch cancelled,
salted inputs, true data dependencies):

  baselines   flash / flash_grad       — the product kernel fwd, fwd+bwd
              xla / xla_grad           — plain XLA attention (unfused)
              stock / stock_grad       — jax.experimental.pallas.ops.tpu
                                         .flash_attention (independent
                                         implementation, same hardware —
                                         the honest external ceiling)
  moves       bf16sm                   — FLASH_MXU_BF16=1: bf16 dot
                                         operands + bf16 p with f32
                                         row-max/lse only (the judge's
                                         move (b); spawn fresh process,
                                         env is trace-time)
              pack2                    — two heads packed into one
                                         128-deep contraction (move (a))
              blocks:BQxBK             — fwd block-size override
                                         (move (c): q-block widening)

Shapes: ``--shape bert-large`` (B8 H16 S512 D64, non-causal) and
``--shape gpt2`` (B16 H12 S1024 D64, causal) — the bench headline
attention shapes — plus ``--shape longseq16k`` (B1 H8 S16384 D128,
causal), the docs/benchmarks.md long-context row on the multi-block
general path (regression guard for the single-block specialization).

Run:  python tools/flash_vpu_probe.py --shape bert-large --only flash
Each invocation measures ONE variant (a tunnel hiccup loses one row;
drive the set from a shell loop). Prints one JSON line.
"""

import argparse
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.ops.pallas.flash_attention import (  # noqa: E402
    LANES, LOG2E, NEG_INF, _use_interpret, attention_reference,
    flash_attention)

SHAPES = {
    # (batch, heads, seq, head_dim, causal) — the bench headline configs
    "bert-large": (8, 16, 512, 64, False),
    "gpt2": (16, 12, 1024, 64, True),
    # the docs/benchmarks.md long-context row (r1): multi-k-block
    # GENERAL path — regression guard for the single-block work
    "longseq16k": (1, 8, 16384, 128, True),
}
ITERS = 8
ROUNDS = 6
PEAK = 197e12  # v5e bf16


def attn_flops(b, h, s, d, causal):
    # fwd QK^T + PV: 2 dots x 2 MACs; causal counts the half matrix
    # (MODEL-FLOPs convention, same as bench.py)
    f = 2 * 2 * b * h * s * s * d
    return f // 2 if causal else f


# ---------------------------------------------------------------------------
# pack2: two heads per kernel step, one 128-deep contraction (move (a)).
# Layout (built outside the kernel):
#   q2[b, hp, 0:S,  0:64 ] = q[b, 2hp];   q2[b, hp, S:2S, 64:128] = q[b, 2hp+1]
#   (zeros elsewhere)  -> QK^T of (2S, 128) x (128, S) stacks BOTH heads'
#   score tiles with a full 128-lane contraction.
#   k2/v2[b, hp] = concat(k[b, 2hp], k[b, 2hp+1], lanes)
# PV runs (2S, S) x (S, 128); rows 0:S keep lanes 0:64, rows S:2S keep
# 64:128 (static per q-block since S % block_q == 0). The packing DOUBLES
# the MAC volume of both dots (the zero half of q2 and the discarded half
# of PV), so it wins only if the 64-deep contraction ran below half rate
# or per-step overhead dominates — exactly what this row measures.
# ---------------------------------------------------------------------------


def _pack2_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, block_q, seq):
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * (sm_scale * LOG2E)
    k = k_ref[0, 0, :, :].astype(jnp.float32)   # (S, 128)
    v = v_ref[0, 0, :, :].astype(jnp.float32)   # (S, 128)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, S)
    m = jnp.max(s, axis=-1)
    p = jnp.exp2(s - m[:, None])
    l = jnp.sum(p, axis=-1)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq,128)
    o = o / l[:, None]
    # rows of head A (global row < S) keep lanes 0:64; head B rows keep
    # 64:128. block_q divides S, so the choice is uniform per block —
    # but q-block ids are dynamic, so select with a where on the block id.
    first_half = (qi * block_q) < seq
    lo = o[:, :64]
    hi = o[:, 64:]
    o_ref[0, 0, :, :] = jnp.where(first_half, lo, hi).astype(o_ref.dtype)


def pack2_attention(q, k, v, sm_scale, block_q=512):
    b, h, s, d = q.shape
    assert d == 64 and h % 2 == 0
    hp = h // 2
    # build packed operands (XLA ops; counted inside the measured chain —
    # the packing cost is part of the move's honest price)
    qp = q.reshape(b, hp, 2, s, d)
    zeros = jnp.zeros_like(qp)
    top = jnp.concatenate([qp[:, :, 0], zeros[:, :, 0]], axis=-1)
    bot = jnp.concatenate([zeros[:, :, 1], qp[:, :, 1]], axis=-1)
    q2 = jnp.concatenate([top, bot], axis=2)            # (b, hp, 2S, 128)
    k2 = jnp.concatenate([k.reshape(b, hp, 2, s, d)[:, :, 0],
                          k.reshape(b, hp, 2, s, d)[:, :, 1]], axis=-1)
    v2 = jnp.concatenate([v.reshape(b, hp, 2, s, d)[:, :, 0],
                          v.reshape(b, hp, 2, s, d)[:, :, 1]], axis=-1)

    block_q = min(block_q, s)
    grid = (b, hp, (2 * s) // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b_, h_, i: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, s, 128), lambda b_, h_, i: (b_, h_, 0, 0))
    o_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    o2 = pl.pallas_call(
        functools.partial(_pack2_kernel, sm_scale=sm_scale,
                          block_q=block_q, seq=s),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct((b, hp, 2 * s, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",) * 3),
        interpret=_use_interpret(),
    )(q2, k2, v2)
    return o2.reshape(b, hp, 2, s, d).reshape(b, h, s, d)


# ---------------------------------------------------------------------------
# simple1: the pack2 kernel WITHOUT packing — one head per step, d=64,
# single k-block, no online-softmax scratch, no lse output. Isolates how
# much of pack2's win is the 128-deep contraction vs the single-block
# simplification (direct softmax, no m/l scratch, no lse write).
# ---------------------------------------------------------------------------


def _simple1_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale):
    q = q_ref[0, 0, :, :].astype(jnp.float32) * (sm_scale * LOG2E)
    k = k_ref[0, 0, :, :].astype(jnp.float32)
    v = v_ref[0, 0, :, :].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m = jnp.max(s, axis=-1)
    p = jnp.exp2(s - m[:, None])
    l = jnp.sum(p, axis=-1)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0, :, :] = (o / l[:, None]).astype(o_ref.dtype)


def _simple1_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale):
    q = q_ref[0, 0, :, :].astype(jnp.float32) * (sm_scale * LOG2E)
    k = k_ref[0, 0, :, :].astype(jnp.float32)
    v = v_ref[0, 0, :, :].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m = jnp.max(s, axis=-1)
    p = jnp.exp2(s - m[:, None])
    l = jnp.sum(p, axis=-1)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0, :, :] = (o / l[:, None]).astype(o_ref.dtype)
    lse = m * (1.0 / LOG2E) + jnp.log(l)
    lse_ref[0, 0, :, :] = jax.lax.broadcast_in_dim(
        lse, lse_ref.shape[2:], (0,))


def simple1_lse_attention(q, k, v, sm_scale, block_q=512):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    grid = (b, h, s // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, LANES),
                            lambda b_, h_, i: (b_, h_, i, 0))
    o, lse = pl.pallas_call(
        functools.partial(_simple1_lse_kernel, sm_scale=sm_scale),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",) * 3),
        interpret=_use_interpret(),
    )(q, k, v)
    return o


def simple1_attention(q, k, v, sm_scale, block_q=512):
    b, h, s, d = q.shape
    block_q = min(block_q, s)
    grid = (b, h, s // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0))
    return pl.pallas_call(
        functools.partial(_simple1_kernel, sm_scale=sm_scale),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",) * 3),
        interpret=_use_interpret(),
    )(q, k, v)


# ---------------------------------------------------------------------------
# slope measurement (protocol of tools/bert_decompose.py)
# ---------------------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="bert-large", choices=sorted(SHAPES))
    ap.add_argument("--only", required=True,
                    help="flash|flash_grad|xla|xla_grad|stock|stock_grad|"
                         "pack2|simple1|simple1_lse|blocks:BQxBK|"
                         "blocks_grad:BQxBK")
    cli = ap.parse_args()
    b, h, s, d, causal = SHAPES[cli.shape]
    sm = 1.0 / float(np.sqrt(d))

    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(
        rng.randn(b, h, s, d).astype(np.float32) * 0.3, jnp.bfloat16)
    q0, k0, v0 = mk(), mk(), mk()

    name = cli.only
    blocks = None
    if name.startswith("blocks"):
        kind, spec = name.split(":")
        bq, bk = (int(x) for x in spec.split("x"))
        blocks = (bq, bk)
        name = "flash_grad" if kind.endswith("_grad") else "flash"

    def attn(qc):
        if name in ("flash", "flash_grad"):
            kw = {}
            if blocks:
                kw = {"block_q": blocks[0], "block_k": blocks[1],
                      "bwd_block_q": blocks[0], "bwd_block_k": blocks[1]}
            return flash_attention(qc, k0, v0, causal=causal, **kw)
        if name in ("xla", "xla_grad"):
            return attention_reference(qc, k0, v0, causal=causal)
        if name in ("stock", "stock_grad"):
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as stock)
            return stock(qc, k0, v0, causal=causal, sm_scale=sm)
        if name == "pack2":
            assert not causal, "pack2 probe is non-causal (bert shape)"
            return pack2_attention(qc, k0, v0, sm)
        if name == "simple1":
            assert not causal, "simple1 probe is non-causal (bert shape)"
            return simple1_attention(qc, k0, v0, sm)
        if name == "simple1_lse":
            assert not causal
            return simple1_lse_attention(qc, k0, v0, sm)
        raise SystemExit(f"unknown variant {cli.only}")

    grad_mode = name.endswith("_grad")
    # LAYERS amplifies per-iteration work above the tunnel's timing
    # noise, same as bert_decompose's 24-layer chains; the reported ms
    # is per single attention call.
    LAYERS = 12

    def stack(x):
        for _ in range(LAYERS):
            x = attn(x)
        return x

    @functools.partial(jax.jit, static_argnames="iters")
    def chain(qc, salt, iters):
        if grad_mode:
            def loss(x):
                return jnp.mean(stack(x).astype(jnp.float32))

            def body(x, _):
                out, g = jax.value_and_grad(loss)(x)
                return (x - 1e-6 * g.astype(x.dtype)
                        + jnp.asarray(salt * 1e-12, x.dtype)), out
        else:
            def body(x, _):
                o = stack(x)
                out = jnp.mean(o[:, 0, 0, :].astype(jnp.float32))
                return x + (1e-6 * out + salt).astype(x.dtype), out

        xf, outs = jax.lax.scan(body, qc, None, length=iters)
        return outs[-1]

    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    for iters in (ITERS, 2 * ITERS):
        float(chain(q0, fresh_salt(), iters=iters))
    slopes = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        float(chain(q0, fresh_salt(), iters=ITERS))
        t1 = time.perf_counter()
        float(chain(q0, fresh_salt(), iters=2 * ITERS))
        t2 = time.perf_counter()
        slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
    t = float(np.median(slopes))

    t /= LAYERS  # per single attention call
    flops = attn_flops(b, h, s, d, causal)
    if grad_mode:
        flops *= 3  # bwd recomputes s + 4 dots ~= 2x fwd
    print(json.dumps({
        "shape": cli.shape, "variant": cli.only,
        "ms": round(t * 1e3, 3),
        "mfu": round(flops / t / PEAK, 4),
        "mxu_bf16_env": os.environ.get("FLASH_MXU_BF16", "0"),
    }), flush=True)


if __name__ == "__main__":
    main()
