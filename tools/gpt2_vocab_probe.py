#!/usr/bin/env python
"""Isolate the GPT-2 bench's vocab-projection + causal-loss cost.

Completes the round-4 evidence for the causal headline: BERT-Large's
decomposition (tools/bert_decompose.py) pinned its non-MXU time on the
optimizer and attention; GPT-2's remaining large term is the tied vocab
head — (B·S, 768) @ (768, 50257) plus the 3.3 GB f32 logits round trip
through softmax-xent — which, unlike MLM, cannot be gathered away
(every position is a prediction) and measured SLOWER when chunked
(docs/perf_experiments.md). This probe slope-times that head alone on a
fixed hidden tensor at the bench shape, fwd and fwd+bwd.
"""

import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.models.transformer import causal_lm_loss  # noqa: E402

B, S, D, VOCAB = 16, 1024, 768, 50257
ITERS = 8
ROUNDS = 6


def main():
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(B, S, D), jnp.bfloat16)
    emb = jnp.asarray(rng.randn(VOCAB, D) * 0.02, jnp.float32)
    tokens = jnp.asarray(rng.randint(0, VOCAB, (B, S)), jnp.int32)

    def head(h, e):
        logits = (h @ e.astype(h.dtype).T).astype(jnp.float32)
        return causal_lm_loss(logits, tokens)

    @partial(jax.jit, static_argnames="iters")
    def fwd_chain(h, e, salt, iters):
        def body(h_c, _):
            loss = head(h_c, e)
            return h_c * (1 + 1e-9 * (loss + salt)).astype(h_c.dtype), loss

        _, losses = jax.lax.scan(body, h, None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def grad_chain(h, e, salt, iters):
        def body(carry, _):
            h_c, e_c = carry
            loss, (gh, ge) = jax.value_and_grad(head, argnums=(0, 1))(
                h_c, e_c)
            h_c = h_c - 1e-9 * gh.astype(h_c.dtype)
            e_c = e_c - 1e-9 * ge + salt * 1e-12
            return (h_c, e_c), loss

        _, losses = jax.lax.scan(body, (h, e), None, length=iters)
        return losses[-1]

    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    res = {"batch": B, "seq": S, "vocab": VOCAB}
    for label, fn, fnargs in (("fwd", fwd_chain, (hidden, emb)),
                              ("fwd_bwd", grad_chain, (hidden, emb))):
        for iters in (ITERS, 2 * ITERS):
            float(fn(*fnargs, fresh_salt(), iters=iters))
        slopes = []
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            float(fn(*fnargs, fresh_salt(), iters=ITERS))
            t1 = time.perf_counter()
            float(fn(*fnargs, fresh_salt(), iters=2 * ITERS))
            t2 = time.perf_counter()
            slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
        res[f"{label}_ms"] = round(float(np.median(slopes)) * 1e3, 2)
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
