#!/usr/bin/env python
"""Hierarchy benchmark — flat vs hierarchical host collectives A/B.

ISSUE 18 tentpole evidence: on a multi-host world whose cross-group link
is slower than the intra-group one (here simulated with
``HOROVOD_FAULT_INJECT=netdelay:<ms>:hop=cross`` — the sleep scales with
the number of slow-link crossings each algorithm actually performs, see
utils/resilience.py), the two-level decomposition (intra-group
reduce-scatter -> cross-group exchange over 1/G of the bytes -> intra
allgather) plus an fp16 wire codec on JUST the slow hop should beat the
flat ring end-to-end. Without netdelay (loopback sockets, every hop
equal) flat vs hierarchical should be near parity — the hierarchy only
pays off when the topology is actually lopsided, and the bench reports
both so that claim is checkable.

Phases per payload size (np ranks, group size 2, real multi-process
world over the native wire like tools/control_plane_bench.py):

  * flat            — seed ring allreduce
  * hier            — hierarchical, no compression
  * hier+fp16       — hierarchical, bf16 wire on the cross hop
  * each of the above again under netdelay on the cross hop
  * autotuned       — full mode only: HOROVOD_AUTOTUNE=1 under netdelay
                      for a fixed step budget, then timed; reported as a
                      ratio vs the hand-tuned (hier+fp16) configuration
                      (acceptance: converges within ~5%)

Run:  python tools/hierarchy_bench.py [--np 4] [--tiny]
Emits one JSON object on stdout; ``bench.py --hierarchy`` wraps it into
per-metric lines. The throttled-hop speedup row is emitted with unit
"x" so tools/bench_compare.py gates it higher-is-better.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# f32 element counts per payload; tiny = the tier-1 smoke (numbers
# meaningless, shape of the artifact identical)
SIZES = (65536, 1 << 20)
TINY_SIZES = (16384,)
STEPS, WARMUP = 10, 3
TINY_STEPS, TINY_WARMUP = 4, 2
NETDELAY_MS = 3.0
TINY_NETDELAY_MS = 2.0
# fixed autotune step budget: categorical phase (3 knobs x 2 values x 5
# samples) + warmup + BO samples all fit well inside this, and a FIXED
# count keeps every rank's enqueue sequence identical (breaking on the
# locally-observed freeze bit could skew op counts across ranks by a
# cycle and deadlock the collective)
AUTOTUNE_BUDGET_STEPS = 160


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def worker() -> None:
    sys.path.insert(0, REPO)
    import horovod_tpu as hvd
    from horovod_tpu.core import state

    hvd.init()
    rank = hvd.rank()
    sizes = json.loads(os.environ["HIER_BENCH_SIZES"])
    steps = int(os.environ["HIER_BENCH_STEPS"])
    warmup = int(os.environ["HIER_BENCH_WARMUP"])
    tune_budget = int(os.environ.get("HIER_BENCH_TUNE_BUDGET", "0"))

    results = {}
    if tune_budget:
        # drive the tuner through its schedule on the largest payload;
        # the timed windows below then measure the converged config
        a = np.ones(int(sizes[-1]), np.float32)
        for _ in range(tune_budget):
            hvd.allreduce(a, name="tune/x")
        rt = state.global_state().runtime
        results["autotune_frozen"] = not rt._autotune_active
        pm = rt.param_manager
        if pm is not None:  # coordinator
            results["autotune_best"] = {
                "hierarchical_allreduce":
                    bool(pm.best.hierarchical_allreduce),
                "hierarchy_compression": pm.best.hierarchy_compression,
                "score": round(float(pm.best_score), 3),
            }
    for n in sizes:
        a = np.ones(int(n), np.float32)
        name = f"p{n}"
        for _ in range(warmup):
            hvd.allreduce(a, name=name)
        t0 = time.perf_counter()
        for _ in range(steps):
            hvd.allreduce(a, name=name)
        results[str(n)] = (time.perf_counter() - t0) / steps
    hvd.shutdown()
    if rank == 0:
        print("RESULTS " + json.dumps(results), flush=True)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def launch(world: int, extra_env: dict, timeout: float = 600.0):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_CONTROLLER": "socket",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
        })
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            if p.returncode != 0:
                raise RuntimeError(
                    f"worker failed rc={p.returncode}:\n{out}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULTS "):
                return json.loads(line[len("RESULTS "):])
    raise RuntimeError("no RESULTS line from rank 0:\n" + "\n".join(outs))


def main(world: int, tiny: bool = False) -> dict:
    if world < 4:
        raise SystemExit("--np must be >= 4 (two groups of two)")
    sizes = TINY_SIZES if tiny else SIZES
    steps, warmup = (TINY_STEPS, TINY_WARMUP) if tiny else (STEPS, WARMUP)
    delay_ms = TINY_NETDELAY_MS if tiny else NETDELAY_MS
    base = {
        "HIER_BENCH_SIZES": json.dumps(list(sizes)),
        "HIER_BENCH_STEPS": str(steps),
        "HIER_BENCH_WARMUP": str(warmup),
    }
    flat_env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "0", **base}
    hier_env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                "HOROVOD_HIERARCHY_GROUP_SIZE": "2", **base}
    comp_env = {**hier_env, "HOROVOD_HIERARCHY_COMPRESSION": "fp16"}
    netdelay = {"HOROVOD_FAULT_INJECT": f"netdelay:{delay_ms}:hop=cross"}

    phases = {
        "flat": launch(world, flat_env),
        "hier": launch(world, hier_env),
        "hier_fp16": launch(world, comp_env),
        "flat_netdelay": launch(world, {**flat_env, **netdelay}),
        "hier_netdelay": launch(world, {**hier_env, **netdelay}),
        "hier_fp16_netdelay": launch(world, {**comp_env, **netdelay}),
    }
    big = str(sizes[-1])
    out = {
        "world": world,
        "group_size": 2,
        "netdelay_ms": delay_ms,
        "sizes": list(sizes),
        "us_per_op": {
            ph: {s: round(r[s] * 1e6, 1) for s in map(str, sizes)}
            for ph, r in phases.items()
        },
        # the headline gates: hierarchical win on the throttled hop
        # (higher is better), near-parity on the uniform loopback wire
        "throttled_hop_speedup_x": round(
            phases["flat_netdelay"][big]
            / max(phases["hier_fp16_netdelay"][big], 1e-9), 2),
        "uniform_wire_ratio_x": round(
            phases["flat"][big] / max(phases["hier"][big], 1e-9), 2),
    }
    if tiny:
        out["tiny"] = True
    else:
        # the autotuner, started flat + uncompressed, must find the
        # hierarchical+compressed configuration on its own under the
        # throttled cross hop and land within ~5% of hand-tuned
        tuned = launch(world, {
            **flat_env, **netdelay,
            "HIER_BENCH_TUNE_BUDGET": str(AUTOTUNE_BUDGET_STEPS),
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "1",
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "4",
        }, timeout=900.0)
        out["autotune_frozen"] = tuned.get("autotune_frozen")
        out["autotune_best"] = tuned.get("autotune_best")
        out["autotuned_vs_hand_tuned_x"] = round(
            phases["hier_fp16_netdelay"][big]
            / max(tuned[big], 1e-9), 2)
    return out


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--np", type=int, default=4)
    parser.add_argument("--tiny", action="store_true",
                        help="one small size, few steps, no autotune "
                             "phase — the tier-1 smoke mode")
    cli = parser.parse_args()
    if cli.worker:
        worker()
    else:
        print(json.dumps(main(cli.np, tiny=cli.tiny)), flush=True)
