#!/usr/bin/env python
"""hvd-analyze: concurrency & collective-safety analysis over horovod_tpu.

Runs the static passes (lock-order graph + blocking-under-lock +
guarded-by checking, SPMD collective-divergence lint) against the
checked-in baseline. New findings fail the run (exit 1); baseline
suppressions are enumerated with their review reasons; stale
suppressions (code fixed, entry remains) are reported so the baseline
shrinks over time.

Usage:
  python tools/hvd_analyze.py                      # analyze horovod_tpu/
  python tools/hvd_analyze.py path1 path2 ...      # analyze specific paths
  python tools/hvd_analyze.py --json               # machine-readable report
  python tools/hvd_analyze.py --update-baseline    # accept current findings
  python tools/hvd_analyze.py --no-baseline        # raw findings, exit 1 if any

Exit codes: 0 clean, 1 new findings (or stale suppressions), 2 usage error.

The static passes are jax-free; this script stubs the heavy package
__init__ so it runs in CI without importing jax.
"""

import argparse
import json
import os
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "analysis_baseline.json")


def _import_analysis():
    """Import horovod_tpu.analysis without executing horovod_tpu/__init__
    (which pulls in jax). If the package is already imported — e.g. when
    called from the test suite — use it as-is."""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    if "horovod_tpu" not in sys.modules:
        pkg = types.ModuleType("horovod_tpu")
        pkg.__path__ = [os.path.join(REPO_ROOT, "horovod_tpu")]
        sys.modules["horovod_tpu"] = pkg
    import horovod_tpu.analysis as analysis
    return analysis


def main(argv=None):
    p = argparse.ArgumentParser(prog="hvd_analyze", description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   default=None, help="files/dirs to analyze (default: horovod_tpu/)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline json path (default: tools/analysis_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding, exit 1 if any")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline accepting every current finding "
                        "(existing reasons are preserved; new entries get a "
                        "TODO reason that review must replace)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a json report on stdout")
    args = p.parse_args(argv)

    analysis = _import_analysis()
    paths = args.paths or [os.path.join(REPO_ROOT, "horovod_tpu")]
    for path in paths:
        if not os.path.exists(path):
            print(f"hvd_analyze: no such path: {path}", file=sys.stderr)
            return 2

    findings, edges = analysis.run_static_passes(paths, root=REPO_ROOT)

    if args.update_baseline:
        old = {}
        try:
            old = analysis.baseline.load(args.baseline)
        except (ValueError, OSError):
            pass
        reasons = {fp: e.get("reason", "") for fp, e in old.items() if e.get("reason")}
        analysis.baseline.write(args.baseline, findings, reasons=reasons)
        print(f"hvd_analyze: wrote {len(findings)} suppressions to {args.baseline}")
        return 0

    if args.no_baseline:
        base = {}
    else:
        try:
            base = analysis.baseline.load(args.baseline)
        except ValueError as e:
            print(f"hvd_analyze: {e}", file=sys.stderr)
            return 2
    new, suppressed, stale = analysis.baseline.compare(findings, base)

    if args.as_json:
        json.dump({
            "new": [f.to_dict() for f in new],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_suppressions": stale,
            "lock_order_edges": ["%s->%s" % e for e in edges],
        }, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in new:
            print(f"NEW: {f.render()}  [fingerprint {f.fingerprint}]",
                  file=sys.stderr)
        for f in suppressed:
            reason = base[f.fingerprint].get("reason", "")
            print(f"suppressed: {f.render()}  — {reason}")
        for e in stale:
            print(f"STALE suppression {e['fingerprint']} ({e.get('rule')} in "
                  f"{e.get('file')}): code no longer trips the analyzer — "
                  f"remove it from the baseline", file=sys.stderr)
        print(f"hvd_analyze: {len(new)} new, {len(suppressed)} suppressed, "
              f"{len(stale)} stale, {len(edges)} lock-order edges")

    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
