#!/usr/bin/env python
"""hvd_top: curses-free live memory/throughput view across ranks.

Polls each rank's metrics endpoint (``GET /memory`` for the per-subsystem
ledger + device truth, ``GET /metrics`` for a couple of headline rates,
``GET /comms`` for the per-lane bus-bandwidth panel, and — when the
serving plane is live — ``GET /slo`` + ``GET /serve`` for the SLO
panel) and renders one table per refresh — plain ANSI-free text,
so it works in a dumb terminal, under ``watch``, or piped to a log.

    python tools/hvd_top.py host1:9100 host2:9100
    python tools/hvd_top.py --interval 5 :9100          # localhost
    python tools/hvd_top.py --once :9100                # single snapshot

Endpoints come from ``HOROVOD_METRICS_PORT`` on each worker
(docs/metrics.md); the memory plane behind ``/memory`` is described in
docs/memory.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, List, Optional

POLL_TIMEOUT_SECONDS = 3.0

# ledger columns, widest consumers first; anything else folds into "other"
COLUMNS = ("params", "grads", "param_shards", "grad_shards",
           "optimizer_shards", "serve_kv", "kv_pages", "fusion",
           "ckpt_staging", "program_cache")


def fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "K", "M", "G", "T"):
        if abs(n) < 1024.0 or unit == "T":
            return "%d%s" % (n, unit) if unit == "B" else \
                "%.1f%s" % (n, unit)
        n /= 1024.0
    return str(int(n))


def fetch_json(endpoint: str, route: str) -> Optional[dict]:
    url = "http://%s%s" % (endpoint, route)
    try:
        with urllib.request.urlopen(url, timeout=POLL_TIMEOUT_SECONDS) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def fetch_metric(endpoint: str, text: Optional[str], name: str) -> Optional[float]:
    """One unlabeled sample from an already-fetched /metrics exposition."""
    if text is None:
        return None
    for line in text.splitlines():
        if line.startswith(name + " "):
            try:
                return float(line.split()[1])
            except (ValueError, IndexError):
                return None
    return None


def fetch_metrics_text(endpoint: str) -> Optional[str]:
    url = "http://%s/metrics" % endpoint
    try:
        with urllib.request.urlopen(url, timeout=POLL_TIMEOUT_SECONDS) as r:
            return r.read().decode()
    except Exception:
        return None


def normalize(endpoint: str) -> str:
    endpoint = endpoint.strip()
    if endpoint.startswith(":"):
        return "127.0.0.1" + endpoint
    return endpoint


def discover_routes(endpoints: List[str]) -> Optional[set]:
    """Union of the routes advertised by the endpoints' ``GET /`` route
    index (metrics.py). ``None`` when no endpoint serves an index (an
    older build whose bare root 404s) — callers then probe panels the
    old way instead of skipping them all."""
    routes: set = set()
    any_index = False
    for ep in endpoints:
        idx = fetch_json(ep, "/")
        if isinstance(idx, dict) and isinstance(idx.get("routes"), dict):
            any_index = True
            routes.update(idx["routes"])
    return routes if any_index else None


def panel_wanted(routes: Optional[set], route: str) -> bool:
    """Render the panel backed by ``route``? Yes when some endpoint
    advertises it, or when no route index exists to consult."""
    return routes is None or route in routes


def render(endpoints: List[str]) -> str:
    header = ["rank", "endpoint", "device", "peak", "limit", "drift"]
    header += list(COLUMNS) + ["other", "rss", "oom"]
    rows: List[List[str]] = []
    for ep in endpoints:
        mem = fetch_json(ep, "/memory")
        if mem is None:
            rows.append(["?", ep, "unreachable"] + [""] * (len(header) - 3))
            continue
        subs: Dict[str, dict] = mem.get("subsystems", {})

        def b(name: str) -> Optional[int]:
            rec = subs.get(name)
            return None if rec is None else rec.get("bytes")

        other = sum(int(rec.get("bytes", 0)) for name, rec in subs.items()
                    if name not in COLUMNS and name != "host_rss")
        device = mem.get("device", {})
        in_use = device.get("bytes_in_use") or device.get("live_array_bytes")
        drift = mem.get("reconcile_drift_ratio")
        oom = mem.get("last_oom")
        rows.append(
            [str(mem.get("rank", "?")), ep, fmt_bytes(in_use),
             fmt_bytes(device.get("peak_bytes_in_use") or None),
             fmt_bytes(device.get("bytes_limit") or None),
             ("%+.1f%%" % (100.0 * drift))
             if isinstance(drift, (int, float)) else "-"]
            + [fmt_bytes(b(c)) for c in COLUMNS]
            + [fmt_bytes(other),
               fmt_bytes(b("host_rss")),
               (oom.get("dominant_subsystem", "?") if isinstance(oom, dict)
                else "-")])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows), 1)
              if rows else len(header[i]) for i in range(len(header))]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(header))]
    for r in rows:
        out.append("  ".join(
            (r[i] if i < len(r) else "").ljust(widths[i])
            for i in range(len(header))))
    return "\n".join(out)


def render_slo(endpoints: List[str]) -> str:
    """SLO/serve panel: error budget, burn rate and tail latencies per
    rank (``GET /slo``, docs/tracing.md) plus completed/active request
    counts from ``GET /serve``. Returns "" when no endpoint exposes the
    SLO plane (training-only fleet or pre-tracing build) so the memory
    table stays the whole display."""
    header = ["rank", "endpoint", "scored", "burn", "budget", "alerting",
              "ttft p50/p99", "latency p50/p99", "done", "active", "pages"]
    rows: List[List[str]] = []
    any_slo = False
    for ep in endpoints:
        slo = fetch_json(ep, "/slo")
        if slo is None or "slo" not in slo:
            continue
        any_slo = True
        per_obj: Dict[str, dict] = slo.get("slo", {})
        burns = [o.get("burn_rate") for o in per_obj.values()
                 if isinstance(o.get("burn_rate"), (int, float))]
        budgets = [o.get("error_budget_remaining") for o in per_obj.values()
                   if isinstance(o.get("error_budget_remaining"),
                                 (int, float))]
        alerting = ",".join(sorted(
            name for name, o in per_obj.items() if o.get("alerting"))) or "-"
        lat = slo.get("latency_ms_percentiles") or {}
        ttft = slo.get("ttft_ms_percentiles") or {}

        def pair(p: dict) -> str:
            p50, p99 = p.get("p50"), p.get("p99")
            if not isinstance(p50, (int, float)):
                return "-"
            return "%.0f/%.0f ms" % (p50, p99 if isinstance(
                p99, (int, float)) else p50)

        done = active = None
        pages = "-"
        serve = fetch_json(ep, "/serve")
        if serve is not None:
            reps = [r for h in serve.get("handles", ())
                    for r in h.get("replicas", ())]
            done = sum(int(r.get("completed", 0)) for r in reps)
            active = sum(int(r.get("active", 0)) for r in reps)
            # paged KV pool occupancy (serve/paging.py): used/total
            # summed over the endpoint's paged replicas, "-" for dense
            pools = [r["pages"] for r in reps
                     if isinstance(r.get("pages"), dict)]
            if pools:
                pages = "%d/%d" % (sum(int(p.get("used", 0))
                                       for p in pools),
                                   sum(int(p.get("pages", 0))
                                       for p in pools))
        rows.append(
            [str(slo.get("rank", "?")), ep,
             str(slo.get("requests_scored", 0)),
             ("%.2f" % max(burns)) if burns else "-",
             ("%.2f" % min(budgets)) if budgets else "-",
             alerting, pair(ttft), pair(lat),
             "-" if done is None else str(done),
             "-" if active is None else str(active), pages])
    if not any_slo:
        return ""
    widths = [max(len(header[i]), *(len(r[i]) for r in rows), 1)
              if rows else len(header[i]) for i in range(len(header))]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(header))]
    for r in rows:
        out.append("  ".join(r[i].ljust(widths[i])
                             for i in range(len(header))))
    return "\n".join(out)


def render_comms(endpoints: List[str]) -> str:
    """Comms panel: per-lane bus bandwidth vs roofline utilization per
    rank (``GET /comms``, docs/comms.md). Each cell is
    ``busbw/roofline (util%)`` with a trailing ``!`` while the lane's
    degradation alert is latched. Returns "" when no endpoint exposes
    the comms plane (pre-comms build or HOROVOD_COMMS=0)."""
    lane_names: List[str] = []
    per_ep: List[tuple] = []
    any_comms = False
    for ep in endpoints:
        comms = fetch_json(ep, "/comms")
        if comms is None or "lanes" not in comms:
            continue
        any_comms = True
        lanes: Dict[str, dict] = comms.get("lanes", {})
        per_ep.append((ep, comms))
        for name in lanes:
            if name not in lane_names:
                lane_names.append(name)
    if not any_comms:
        return ""
    lane_names.sort()
    header = ["rank", "endpoint"] + lane_names + ["degraded"]
    rows: List[List[str]] = []
    for ep, comms in per_ep:
        lanes = comms.get("lanes", {})
        cells = []
        for name in lane_names:
            rec = lanes.get(name)
            if not isinstance(rec, dict) or rec.get("busbw_gbs") is None:
                cells.append("-")
                continue
            util = rec.get("utilization")
            cell = "%.2f" % rec["busbw_gbs"]
            if isinstance(util, (int, float)):
                cell += "/%.2f (%.0f%%)" % (
                    rec.get("roofline_gbs") or 0.0, 100.0 * util)
            if rec.get("alerting"):
                cell += "!"
            cells.append(cell)
        degraded = sum(int(rec.get("degraded_count", 0))
                       for rec in lanes.values() if isinstance(rec, dict))
        rows.append([str(comms.get("rank", "?")), ep] + cells
                    + [str(degraded)])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows), 1)
              if rows else len(header[i]) for i in range(len(header))]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(header))]
    for r in rows:
        out.append("  ".join(r[i].ljust(widths[i])
                             for i in range(len(header))))
    return "\n".join(out)


def render_goodput(endpoints: List[str]) -> str:
    """Goodput panel: productive fraction of wall-clock, top badput
    category and incident counts per rank (``GET /goodput``,
    docs/goodput.md), plus the most recent incident across the fleet.
    Returns "" when no endpoint exposes the goodput plane (pre-goodput
    build or HOROVOD_GOODPUT=0)."""
    header = ["rank", "endpoint", "wall", "goodput", "accounted",
              "top badput", "steps", "replayed", "incidents"]
    rows: List[List[str]] = []
    latest = None  # (wall_time, rank, incident)
    for ep in endpoints:
        gp = fetch_json(ep, "/goodput")
        if gp is None or "goodput_fraction" not in gp:
            continue
        badput: Dict[str, float] = gp.get("badput_seconds") or {}
        top = max(badput, key=badput.get) if badput else None
        incidents = gp.get("incidents") or []
        for inc in incidents:
            if not isinstance(inc, dict):
                continue
            t = inc.get("wall_time")
            if isinstance(t, (int, float)) and \
                    (latest is None or t > latest[0]):
                latest = (t, gp.get("rank", "?"), inc)
        rows.append(
            [str(gp.get("rank", "?")), ep,
             "%.0fs" % gp.get("wall_seconds", 0.0),
             "%.1f%%" % (100.0 * gp.get("goodput_fraction", 0.0)),
             "%.1f%%" % (100.0 * gp.get("accounted_fraction", 0.0)),
             ("%s %.1fs" % (top, badput[top])) if top else "-",
             str(gp.get("steps_productive", 0)),
             str(gp.get("steps_replayed", 0)),
             str(sum((gp.get("incident_counts") or {}).values()))])
    if not rows:
        return ""
    widths = [max(len(header[i]), *(len(r[i]) for r in rows), 1)
              for i in range(len(header))]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(header))]
    for r in rows:
        out.append("  ".join(r[i].ljust(widths[i])
                             for i in range(len(header))))
    if latest is not None:
        _, rank, inc = latest
        out.append("last incident: %s on rank %s — %.1fs%s" % (
            inc.get("cause", "?"), rank,
            float(inc.get("duration_s", 0.0)),
            (", culprit rank %s" % inc["culprit_rank"])
            if inc.get("culprit_rank") is not None else ""))
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="live per-rank memory ledger (polls /memory)")
    parser.add_argument("endpoints", nargs="+", metavar="HOST:PORT",
                        help="metrics endpoints (':9100' = localhost)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="print one snapshot and exit")
    args = parser.parse_args(argv)
    endpoints = [normalize(e) for e in args.endpoints]
    while True:
        stamp = time.strftime("%H:%M:%S")
        print("hvd_top  %s  (%d endpoint%s)" % (
            stamp, len(endpoints), "" if len(endpoints) == 1 else "s"))
        print(render(endpoints))
        # the GET / route index says which panels this fleet can back;
        # with no index (older build) every panel probes as before
        routes = discover_routes(endpoints)
        if panel_wanted(routes, "/comms"):
            comms_panel = render_comms(endpoints)
            if comms_panel:
                print()
                print(comms_panel)
        if panel_wanted(routes, "/goodput"):
            goodput_panel = render_goodput(endpoints)
            if goodput_panel:
                print()
                print(goodput_panel)
        if panel_wanted(routes, "/slo"):
            slo_panel = render_slo(endpoints)
            if slo_panel:
                print()
                print(slo_panel)
        if args.once:
            return 0
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        print()


if __name__ == "__main__":
    sys.exit(main())
