#!/usr/bin/env python
"""Where does the Inception-V3 step actually go? (VERDICT r4 weak #3 /
r5 ask 4: the table's worst MFU row — 22.5% — had no independent
evidence.)

Applies the ResNet evidentiary protocol (tools/resnet_decompose.py):
slope-timed scan chains (dispatch cancelled, salted inputs, scalar
readback) on the bench configuration — batch 32, 299x299, bf16.

Two layers of evidence:

  * step split     — infer / fwd_train / full train step (fwd vs bwd)
  * stage split    — the model's five structural segments timed alone,
                     each with XLA's own cost-analysis FLOPs as the MFU
                     basis (the bench convention). This is the
                     "stock-JAX control" at the only level that is
                     meaningful here: every conv in the model IS stock
                     ``flax.linen.Conv`` (horovod_tpu/models/inception.py
                     wraps nn.Conv + BN and nothing else), so a separate
                     stock implementation would re-measure the same XLA
                     programs; what needs independent evidence is WHICH
                     structural segment burns the MFU.

Segments (input shapes at batch 32):
  stem     299² x3  -> 35² x192   (7 convs + 2 maxpools, 3-channel entry)
  blockA   35²  x192 -> 35² x288  (3x InceptionA: 1x1/5x5/3x3 branches)
  blockBC  35²  x288 -> 17² x768  (B reduction + 4x C: 1x7/7x1 factor.)
  blockDE  17²  x768 -> 8²  x2048 (D reduction + 2x E: 1x3/3x1 forks)
  head     8²   x2048 -> logits   (global mean + dense)

Run:  python tools/inception_decompose.py [--only PHASE]
PHASES: infer fwd full stem blockA blockBC blockDE head
Each --only invocation prints one JSON line (a tunnel hiccup loses one
phase; drive the full set from a shell loop).
"""

import argparse
import json
import os
import sys
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import flax.linen as nn  # noqa: E402

from horovod_tpu import training  # noqa: E402
from horovod_tpu.models.inception import (  # noqa: E402
    ConvBN, InceptionA, InceptionB, InceptionC, InceptionD, InceptionE,
    InceptionV3)

BATCH = 32
ITERS = 12
ROUNDS = 6
PEAK = 197e12  # v5e bf16 (2xMAC convention, same as bench.py)
FWD_FLOPS = BATCH * 11.137e9  # XLA cost analysis of the full forward


class Stem(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        return nn.max_pool(x, (3, 3), strides=(2, 2))


class BlockA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        return InceptionA(64, dtype=self.dtype)(x, train)


class BlockBC(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        x = InceptionB(dtype=self.dtype)(x, train)
        x = InceptionC(128, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        return InceptionC(192, dtype=self.dtype)(x, train)


class BlockDE(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        x = InceptionD(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        return InceptionE(dtype=self.dtype)(x, train)


class Head(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(1000, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


SEGMENTS = {
    # name -> (module, input shape at batch 32)
    "stem": (Stem, (BATCH, 299, 299, 3)),
    "blockA": (BlockA, (BATCH, 35, 35, 192)),
    "blockBC": (BlockBC, (BATCH, 35, 35, 288)),
    "blockDE": (BlockDE, (BATCH, 17, 17, 768)),
    "head": (Head, (BATCH, 8, 8, 2048)),
}


def slope_measure(fn, *args, fresh_salt=None):
    for iters in (ITERS, 2 * ITERS):
        float(fn(*args, fresh_salt(), iters=iters))
    slopes = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        float(fn(*args, fresh_salt(), iters=ITERS))
        t1 = time.perf_counter()
        float(fn(*args, fresh_salt(), iters=2 * ITERS))
        t2 = time.perf_counter()
        slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
    return float(np.median(slopes))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    choices=["infer", "fwd", "full"] + sorted(SEGMENTS))
    cli = ap.parse_args()

    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    measure = partial(slope_measure, fresh_salt=fresh_salt)
    rng = np.random.RandomState(0)
    res = {"batch": BATCH}

    def segment_row(name):
        mod_cls, shape = SEGMENTS[name]
        mod = mod_cls()
        x0 = jnp.asarray(rng.uniform(-1, 1, shape).astype(np.float32))
        variables = training.init_on_host_fn(
            lambda x: mod.init(jax.random.PRNGKey(0), x, train=False),
            np.zeros((1,) + shape[1:], np.float32))
        params = variables["params"]
        stats = variables.get("batch_stats", {})

        def apply_fwd(x):
            out = mod.apply(
                {"params": params, "batch_stats": stats} if stats
                else {"params": params},
                x, train=True,
                **({"mutable": ["batch_stats"]} if stats else {}))
            return out[0] if stats else out

        # fwd-only segment chain: carry the INPUT, perturbed by a scalar
        # of the output (true data dependency, shapes unchanged)
        @partial(jax.jit, static_argnames="iters")
        def seg_chain(x, salt, iters):
            def body(x, _):
                y = apply_fwd(x)
                s = jnp.mean(y.astype(jnp.float32))
                return x + (1e-6 * s + salt).astype(x.dtype), s

            _, outs = jax.lax.scan(body, x, None, length=iters)
            return outs[-1]

        # XLA's own FLOP count for one forward application — the same
        # basis as bench.py's model constants
        flops = jax.jit(apply_fwd).lower(x0).compile() \
            .cost_analysis()["flops"]
        t = measure(seg_chain, x0)
        res[f"{name}_ms"] = round(t * 1e3, 3)
        res[f"{name}_gflops"] = round(float(flops) / 1e9, 2)
        res[f"{name}_mfu"] = round(float(flops) / t / PEAK, 4)

    if cli.only in SEGMENTS:
        segment_row(cli.only)
        print(json.dumps(res), flush=True)
        return

    # ---- whole-model phases (resnet_decompose protocol) ----
    model = InceptionV3(num_classes=1000, dtype=jnp.bfloat16)
    images = jnp.asarray(
        rng.uniform(-1, 1, (BATCH, 299, 299, 3)).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)).astype(np.int32))
    variables = training.init_on_host_fn(
        lambda x: model.init(jax.random.PRNGKey(0), x, train=False),
        np.zeros((1, 299, 299, 3), np.float32))
    params, stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(p, s, x, y):
        logits, mut = model.apply({"params": p, "batch_stats": s}, x,
                                  train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)), \
            mut["batch_stats"]

    @partial(jax.jit, static_argnames="iters")
    def infer_chain(p, s, x, salt, iters):
        x = x + salt

        def body(x, _):
            logits = model.apply({"params": p, "batch_stats": s}, x,
                                 train=False)
            return x + 1e-6 * jnp.mean(logits), logits[0, 0]

        x, outs = jax.lax.scan(body, x, None, length=iters)
        return outs[-1]

    @partial(jax.jit, static_argnames="iters")
    def fwd_train_chain(p, s, x, y, salt, iters):
        x = x + salt

        def body(carry, _):
            x, s = carry
            loss, new_s = loss_fn(p, s, x, y)
            return (x + 1e-6 * loss, new_s), loss

        (x, s), losses = jax.lax.scan(body, (x, s), None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def train_chain(p, s, o, x, y, salt, iters):
        x = x + salt

        def body(carry, _):
            p, s, o = carry
            (loss, new_s), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, s, x, y)
            upd, o = tx.update(g, o, p)
            p = optax.apply_updates(p, upd)
            return (p, new_s, o), loss

        (p, s, o), losses = jax.lax.scan(body, (p, s, o), None,
                                         length=iters)
        return losses[-1]

    phases = {
        "infer": lambda: measure(infer_chain, params, stats, images),
        "fwd": lambda: measure(fwd_train_chain, params, stats, images,
                               labels),
        "full": lambda: measure(train_chain, params, stats, opt_state,
                                images, labels),
    }
    if cli.only:
        t = phases[cli.only]()
        res[f"{cli.only}_ms"] = round(t * 1e3, 2)
        if cli.only == "infer":
            res["infer_mfu"] = round(FWD_FLOPS / t / PEAK, 4)
        if cli.only == "fwd":
            res["fwd_mfu"] = round(FWD_FLOPS / t / PEAK, 4)
        if cli.only == "full":
            res["full_step_mfu"] = round(3 * FWD_FLOPS / t / PEAK, 4)
            res["img_per_sec"] = round(BATCH / t, 1)
        print(json.dumps(res), flush=True)
        return

    t_infer = phases["infer"]()
    t_fwd = phases["fwd"]()
    t_full = phases["full"]()
    res.update({
        "infer_ms": round(t_infer * 1e3, 2),
        "fwd_train_ms": round(t_fwd * 1e3, 2),
        "full_step_ms": round(t_full * 1e3, 2),
        "bwd_plus_update_ms": round((t_full - t_fwd) * 1e3, 2),
        "infer_mfu": round(FWD_FLOPS / t_infer / PEAK, 4),
        "fwd_train_mfu": round(FWD_FLOPS / t_fwd / PEAK, 4),
        "full_step_mfu": round(3 * FWD_FLOPS / t_full / PEAK, 4),
        "img_per_sec": round(BATCH / t_full, 1),
    })
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
