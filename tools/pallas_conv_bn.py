#!/usr/bin/env python
"""Pallas conv + BN-statistics epilogue prototype (VERDICT r2 ask 1b).

Round 2 argued ResNet-50's ~15% MFU is bounded by BN-statistics HBM
traffic: every conv output is written to HBM, then RE-READ for the
batch-stats reduction — a pass that disappears if the stats are an
epilogue of the conv kernel itself. XLA's reduction-into-conv fusion is
not expressible from JAX; this prototype tests whether it is achievable
from Pallas at all, on ResNet-50's most frequent 3x3 shape (stage 3:
14x14x256 -> 256, batch 128 — six bottleneck blocks carry it).

Measures, same chip / same protocol as bench.py (compiled scan chains,
scalar readback):
  A. XLA conv alone                      (the pure-conv floor)
  B. XLA conv + separate stats reduce    (today's decomposition)
  C. Pallas conv with fused sum/sumsq epilogue (one HBM pass)

If C ~= A while B > A by the stats-pass cost, the round-2 structural
argument is confirmed AND the counter-move exists; if C >> B, Pallas
cannot beat XLA's conv emitter from outside and the gap is confirmed
structural at the toolchain level.

Prints one JSON line with the three times and derived verdict numbers.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BATCH = 128
H = W = 14
CIN = COUT = 256
BATCH_TILE = 4
# Timing is slope-based to cancel the remote-dispatch latency of the
# axon tunnel (~100ms/call, which would swamp a ~100us kernel): each
# chain is compiled at two lengths and the per-iteration time is
# (t_long - t_short) / (ITERS_LONG - ITERS_SHORT).
ITERS_SHORT = 100
ITERS_LONG = 600
ROUNDS = 6

# one 3x3 conv at this shape: H*W*9*CIN*COUT MACs per image
FLOPS_PER_APP = 2 * BATCH * H * W * 9 * CIN * COUT


def _conv_kernel(x_ref, w_ref, y_ref, sum_ref, sumsq_ref, acc_ref):
    """One batch-tile of images: 3x3 conv as 9 channel-contraction
    dot_generals over the padded input block, f32 accumulation in VMEM
    scratch, then (a) bf16 output write and (b) per-channel sum / sumsq
    accumulated across grid steps — the BN-stats epilogue that saves the
    HBM re-read."""
    step = pl.program_id(0)

    acc_ref[...] = jnp.zeros_like(acc_ref)
    for dh in range(3):
        for dw in range(3):
            patch = x_ref[:, dh:dh + H, dw:dw + W, :]
            acc_ref[...] += lax.dot_general(
                patch, w_ref[dh, dw],
                dimension_numbers=(((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    acc = acc_ref[...]
    y_ref[...] = acc.astype(jnp.bfloat16)

    @pl.when(step == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sumsq_ref[...] = jnp.zeros_like(sumsq_ref)

    sum_ref[...] += jnp.sum(acc, axis=(0, 1, 2))
    sumsq_ref[...] += jnp.sum(acc * acc, axis=(0, 1, 2))


@jax.jit
def pallas_conv_stats(x_padded, w):
    """x_padded: (BATCH, H+2, W+2, CIN) bf16; w: (3,3,CIN,COUT) bf16.
    Returns (y bf16, channel_sum f32, channel_sumsq f32)."""
    grid = (BATCH // BATCH_TILE,)
    return pl.pallas_call(
        _conv_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BATCH_TILE, H + 2, W + 2, CIN),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, CIN, COUT), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BATCH_TILE, H, W, COUT),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((COUT,), lambda i: (0,)),
            pl.BlockSpec((COUT,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BATCH, H, W, COUT), jnp.bfloat16),
            jax.ShapeDtypeStruct((COUT,), jnp.float32),
            jax.ShapeDtypeStruct((COUT,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BATCH_TILE, H, W, COUT), jnp.float32)],
    )(x_padded, w)


def xla_conv(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


@functools.partial(jax.jit, static_argnames="iters")
def xla_conv_only_chain(x, w, salt, iters):
    x = x + salt.astype(x.dtype)

    def body(x, _):
        y = xla_conv(x, w)
        # feed a scaled slice back so iterations are data-dependent
        # (no cross-iteration CSE) without changing the measured op
        x = x + 1e-6 * y[:, :, :, :CIN].astype(x.dtype)
        return x, ()

    x, _ = lax.scan(body, x, None, length=iters)
    return jnp.sum(x[0, 0, 0, :8].astype(jnp.float32))


@functools.partial(jax.jit, static_argnames="iters")
def xla_conv_stats_chain(x, w, salt, iters):
    x = x + salt.astype(x.dtype)

    def body(x, _):
        y = xla_conv(x, w)
        yf = y.astype(jnp.float32)
        s = jnp.sum(yf, axis=(0, 1, 2))
        ss = jnp.sum(yf * yf, axis=(0, 1, 2))
        x = x + 1e-6 * y[:, :, :, :CIN].astype(x.dtype) \
            + (1e-9 * (s[0] + ss[0])).astype(x.dtype)
        return x, ()

    x, _ = lax.scan(body, x, None, length=iters)
    return jnp.sum(x[0, 0, 0, :8].astype(jnp.float32))


@functools.partial(jax.jit, static_argnames="iters")
def pallas_chain(x_padded, w, salt, iters):
    x_padded = x_padded + salt.astype(x_padded.dtype)

    def body(x_padded, _):
        y, s, ss = pallas_conv_stats(x_padded, w)
        upd = 1e-6 * y[:, :, :, :CIN].astype(x_padded.dtype) \
            + (1e-9 * (s[0] + ss[0])).astype(x_padded.dtype)
        x_padded = x_padded.at[:, 1:1 + H, 1:1 + W, :].add(upd)
        return x_padded, ()

    x_padded, _ = lax.scan(body, x_padded, None, length=iters)
    return jnp.sum(x_padded[0, 1, 1, :8].astype(jnp.float32))


_salt_counter = [0]


def _fresh_salt():
    """Every timed call gets a distinct input value: the remote-dispatch
    tunnel memoizes identical (executable, inputs) executions, so
    repeating a call with unchanged arguments measures the cache, not
    the chip (docs/benchmarks.md protocol)."""
    _salt_counter[0] += 1
    return jnp.float32(_salt_counter[0] * 1e-7)


def time_chain(fn, *args):
    """Per-iteration seconds with dispatch latency cancelled: median over
    ROUNDS of (t[ITERS_LONG] - t[ITERS_SHORT]) / (ITERS_LONG -
    ITERS_SHORT)."""
    for iters in (ITERS_SHORT, ITERS_LONG):  # compile + warm both
        float(fn(*args, _fresh_salt(), iters=iters))
    slopes = []
    for _ in range(ROUNDS):
        # float(...) = scalar readback — through the remote-dispatch
        # tunnel block_until_ready alone does not wait for execution
        t0 = time.perf_counter()
        float(fn(*args, _fresh_salt(), iters=ITERS_SHORT))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(fn(*args, _fresh_salt(), iters=ITERS_LONG))
        t_long = time.perf_counter() - t0
        slopes.append((t_long - t_short) / (ITERS_LONG - ITERS_SHORT))
    return float(np.median(slopes))


def shape_sweep():
    """XLA conv MFU + stats-epilogue cost per ResNet-50 stage shape
    (batch 128, 3x3 convs). Pins down WHERE the end-to-end 15% MFU
    comes from: if the early large-spatial/low-channel stages run at a
    fraction of stage 3/4's MFU in isolation, the model's MFU is shape
    structure, not framework overhead."""
    rng = np.random.RandomState(0)
    rows = []
    for (h, c) in [(56, 64), (28, 128), (14, 256), (7, 512)]:
        x = jnp.asarray(rng.uniform(-1, 1, (BATCH, h, h, c)),
                        dtype=jnp.bfloat16)
        w = jnp.asarray(rng.uniform(-0.1, 0.1, (3, 3, c, c)),
                        dtype=jnp.bfloat16)
        global CIN  # the chain feedback slice width follows the shape
        CIN = c
        t_conv = time_chain(xla_conv_only_chain, x, w)
        t_stats = time_chain(xla_conv_stats_chain, x, w)
        flops = 2 * BATCH * h * h * 9 * c * c
        rows.append({
            "shape": f"{h}x{h}x{c}",
            "xla_conv_us": round(t_conv * 1e6, 1),
            "stats_cost_us": round((t_stats - t_conv) * 1e6, 1),
            "xla_conv_mfu": round(flops / t_conv / 197e12, 4),
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


def main():
    print(f"devices: {jax.devices()}", file=sys.stderr, flush=True)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (BATCH, H, W, CIN)),
                    dtype=jnp.bfloat16)
    w = jnp.asarray(rng.uniform(-0.1, 0.1, (3, 3, CIN, COUT)),
                    dtype=jnp.bfloat16)
    x_padded = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))

    # numeric check vs XLA before timing. The epilogue sums the UNROUNDED
    # f32 accumulator (more accurate than re-reading the rounded bf16
    # output, which is what the separate XLA stats pass does), so the
    # stats reference is an f32 conv of the same bf16 values.
    y_ref = xla_conv(x, w)
    y_pl, s_pl, ss_pl = pallas_conv_stats(x_padded, w)
    np.testing.assert_allclose(np.asarray(y_pl, np.float32),
                               np.asarray(y_ref, np.float32),
                               rtol=2e-2, atol=2e-2)
    yf32 = lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(s_pl),
                               np.asarray(jnp.sum(yf32, axis=(0, 1, 2))),
                               rtol=1e-2, atol=2.0)
    np.testing.assert_allclose(
        np.asarray(ss_pl),
        np.asarray(jnp.sum(yf32 * yf32, axis=(0, 1, 2))),
        rtol=1e-2)
    print("numerics ok", file=sys.stderr, flush=True)

    t_conv = time_chain(xla_conv_only_chain, x, w)
    t_conv_stats = time_chain(xla_conv_stats_chain, x, w)
    t_pallas = time_chain(pallas_chain, x_padded, w)

    result = {
        "shape": f"{BATCH}x{H}x{W}x{CIN}->{COUT} 3x3",
        "xla_conv_us": round(t_conv * 1e6, 1),
        "xla_conv_plus_stats_us": round(t_conv_stats * 1e6, 1),
        "pallas_fused_us": round(t_pallas * 1e6, 1),
        "stats_pass_cost_us": round((t_conv_stats - t_conv) * 1e6, 1),
        "xla_conv_mfu": round(FLOPS_PER_APP / t_conv / 197e12, 4),
        "pallas_fused_mfu": round(FLOPS_PER_APP / t_pallas / 197e12, 4),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", action="store_true",
                        help="per-stage XLA conv shape sweep instead of "
                             "the Pallas comparison")
    if parser.parse_args().sweep:
        shape_sweep()
    else:
        main()
