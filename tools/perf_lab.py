"""ResNet-50 step-time experiment harness (round-2 perf work).

Sweeps TPU compiler options over the SAME lowered bench program —
``jax.jit(...).lower(...).compile(compiler_options=...)`` forwards the
options through the remote-dispatch tunnel to the real TPU compiler
(verified: unknown options are rejected by the remote compile) — and
times each executable with the measurement protocol from
docs/benchmarks.md (multi-step rounds inside one program, scalar-readback
sync, interleaved A/B).

    python tools/perf_lab.py            # run the experiment matrix
    python tools/perf_lab.py '{"xla_tpu_scoped_vmem_limit_kib": "65536"}'
"""

import json
import os
import sys
import time

import jax
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import training  # noqa: E402
from horovod_tpu.models.resnet import ResNet50  # noqa: E402

BATCH = int(os.environ.get("LAB_BATCH", "128"))
STEPS = int(os.environ.get("LAB_STEPS", "20"))
ROUNDS = int(os.environ.get("LAB_ROUNDS", "4"))

# Options the remote TPU compiler accepted in round-2 probing (unknown
# names are rejected by the remote compile with HTTP 500, so additions
# are cheap to validate).
EXPERIMENTS = [
    ("baseline", {}),
    ("rwb_off", {"xla_tpu_rwb_fusion": "false"}),
    ("rwb_sched", {"xla_tpu_rwb_fusion": "false",
                   "xla_tpu_enable_all_experimental_scheduler_features":
                   "true"}),
    ("rwb_barrier", {"xla_tpu_rwb_fusion": "false",
                     "xla_tpu_aggressive_opt_barrier_removal": "true"}),
    ("sched_only", {"xla_tpu_enable_all_experimental_scheduler_features":
                    "true"}),
]


def main():
    hvd.init()
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    opt = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    state = training.create_train_state(model, opt, (1, 224, 224, 3))
    round_fn, batch_sharding = training.make_train_round(
        model, opt, steps=STEPS, donate=False)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        rng.uniform(-1, 1, (BATCH, 224, 224, 3)).astype(np.float32),
        batch_sharding)
    labels = jax.device_put(
        rng.randint(0, 1000, (BATCH,)).astype(np.int32), batch_sharding)
    args = (state.params, state.batch_stats, state.opt_state, images, labels)

    print("lowering...", file=sys.stderr, flush=True)
    lowered = round_fn.lower(*args)

    if len(sys.argv) > 1:
        experiments = [("cli", json.loads(sys.argv[1]))]
    else:
        experiments = EXPERIMENTS

    compiled = {}
    for name, options in experiments:
        t0 = time.perf_counter()
        try:
            compiled[name] = lowered.compile(
                compiler_options=options or None)
            print(f"compiled {name} in {time.perf_counter() - t0:.1f}s",
                  file=sys.stderr, flush=True)
        except Exception as e:
            print(f"REJECTED {name}: {str(e)[:160]}", file=sys.stderr,
                  flush=True)

    # Interleave all surviving executables round-robin (A/B protocol:
    # run-to-run drift hits every variant equally). Each executable
    # chains ITS OWN evolving state forward — identical (program, inputs)
    # re-dispatches are served from the tunnel's cache and time absurdly
    # fast (docs/benchmarks.md measurement protocol) — and every timed
    # call ends in a scalar readback as the sync point.
    states = {}
    for name, ex in compiled.items():  # warmup + per-exp state
        t0 = time.perf_counter()
        loss, p, s, o = ex(*args)
        float(loss)
        print(f"warmup {name}: {time.perf_counter() - t0:.2f}s",
              file=sys.stderr, flush=True)
        states[name] = (p, s, o)
    results = {name: [] for name in compiled}
    for r in range(ROUNDS):
        for name, ex in compiled.items():
            p, s, o = states[name]
            t0 = time.perf_counter()
            loss, p, s, o = ex(p, s, o, images, labels)
            float(loss)
            dt = time.perf_counter() - t0
            states[name] = (p, s, o)
            results[name].append(BATCH * STEPS / dt)
    for name in results:
        rates = results[name]
        print(json.dumps({
            "exp": name, "img_per_sec": round(float(np.median(rates)), 1),
            "all": [round(r, 1) for r in rates]}), flush=True)


if __name__ == "__main__":
    main()
