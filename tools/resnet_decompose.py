#!/usr/bin/env python
"""Where does the ResNet-50 step actually go? Forward vs full-step split.

Complements the conv microbenchmarks (tools/pallas_conv_bn.py): isolated
3x3 convs run at 75-100% MFU with free stats epilogues, so the
end-to-end ~15% MFU must live in the backward pass + elementwise
structure. This measures, on the bench model itself (batch 128, bf16):

  * forward-only inference step (train=False, no stats update)
  * forward + loss + BN-stats (train=True forward)
  * the full training step (fwd + bwd + SGD update) — bench.py's op

Same scan-chain + scalar-readback + salted-inputs protocol as the other
tools (the tunnel memoizes identical calls).
"""

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, "/root/repo")

from horovod_tpu.models.resnet import ResNet50  # noqa: E402

BATCH = 128
ITERS = 20
ROUNDS = 6
FWD_FLOPS = BATCH * 4.089e9
TRAIN_FLOPS = 3 * FWD_FLOPS


def main():
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    images = jnp.asarray(
        rng.uniform(-1, 1, (BATCH, 224, 224, 3)).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1000, (BATCH,)).astype(np.int32))
    variables = model.init(jax.random.PRNGKey(0), images[:1], train=False)
    params, stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(p, s, x, y):
        logits, mut = model.apply({"params": p, "batch_stats": s}, x,
                                  train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1)), \
            mut["batch_stats"]

    @partial(jax.jit, static_argnames="iters")
    def infer_chain(p, s, x, salt, iters):
        x = x + salt

        def body(x, _):
            logits = model.apply({"params": p, "batch_stats": s}, x,
                                 train=False)
            return x + 1e-6 * jnp.mean(logits), logits[0, 0]

        x, outs = jax.lax.scan(body, x, None, length=iters)
        return outs[-1]

    @partial(jax.jit, static_argnames="iters")
    def fwd_train_chain(p, s, x, y, salt, iters):
        x = x + salt

        def body(carry, _):
            x, s = carry
            loss, new_s = loss_fn(p, s, x, y)
            return (x + 1e-6 * loss, new_s), loss

        (x, s), losses = jax.lax.scan(body, (x, s), None, length=iters)
        return losses[-1]

    @partial(jax.jit, static_argnames="iters")
    def train_chain(p, s, o, x, y, salt, iters):
        x = x + salt

        def body(carry, _):
            p, s, o = carry
            (loss, new_s), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, s, x, y)
            upd, o = tx.update(g, o, p)
            p = optax.apply_updates(p, upd)
            return (p, new_s, o), loss

        (p, s, o), losses = jax.lax.scan(body, (p, s, o), None,
                                         length=iters)
        return losses[-1]

    salt_n = [0]

    def fresh_salt():
        salt_n[0] += 1
        return jnp.float32(salt_n[0] * 1e-7)

    def measure(fn, *args):
        for iters in (ITERS, 2 * ITERS):
            float(fn(*args, fresh_salt(), iters=iters))
        slopes = []
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            float(fn(*args, fresh_salt(), iters=ITERS))
            t1 = time.perf_counter()
            float(fn(*args, fresh_salt(), iters=2 * ITERS))
            t2 = time.perf_counter()
            slopes.append(((t2 - t1) - (t1 - t0)) / ITERS)
        return float(np.median(slopes))

    t_infer = measure(infer_chain, params, stats, images)
    t_fwd = measure(fwd_train_chain, params, stats, images, labels)
    t_full = measure(train_chain, params, stats, opt_state, images, labels)

    print(json.dumps({
        "batch": BATCH,
        "infer_ms": round(t_infer * 1e3, 2),
        "fwd_train_ms": round(t_fwd * 1e3, 2),
        "full_step_ms": round(t_full * 1e3, 2),
        "bwd_plus_update_ms": round((t_full - t_fwd) * 1e3, 2),
        "infer_mfu": round(FWD_FLOPS / t_infer / 197e12, 4),
        "fwd_train_mfu": round(FWD_FLOPS / t_fwd / 197e12, 4),
        "full_step_mfu": round(TRAIN_FLOPS / t_full / 197e12, 4),
        "img_per_sec": round(BATCH / t_full, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
