"""Worker script for the serving-plane chaos cell
(``serve_kill_replica`` in tools/chaos_matrix.py).

Rank 0 is the FRONTEND/load generator: it drives a
:class:`~horovod_tpu.serve.queue.KVQueueFrontend` against the matrix's
rendezvous store, submits ``CHAOS_SERVE_REQUESTS`` generation requests
round-robin across the replica fleet, and keeps polling until every
request completes — re-dispatching the un-answered requests of any
replica whose heartbeat lapses. It is the only rank that prints
``CHAOS_RESULT``; the invariants the matrix asserts:

* ``zero_lost`` — every submitted request completed, despite the kill;
* ``requeued``  — the dead replica's in-flight requests really were
  redistributed (nonzero), not silently never-assigned.

Ranks >= 1 are serving replicas: each builds the same tiny
deterministic transformer (seed 0 — replicas must agree on params) and
runs :func:`~horovod_tpu.serve.replica.run_kv_replica` until rank 0
publishes the stop key. ``HOROVOD_FAULT_INJECT=kill:rank=2:step=5``
fires on the victim's 5th DECODE step (the serving step counter), so
the kill lands mid-generation with work in flight. No ``hvd.init()``
anywhere — the serving plane rides the KV store alone, which is itself
part of what the cell proves.
"""

import json
import os
import sys
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REQUESTS = int(os.environ.get("CHAOS_SERVE_REQUESTS", "30"))
DRAIN_TIMEOUT = float(os.environ.get("CHAOS_SERVE_TIMEOUT", "150"))

MODEL = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=2,
             d_ff=64, max_seq=64, causal=True)


def _metric_total(snap, name):
    fam = snap.get(name, {})
    return float(sum(row.get("value", 0.0)
                     for row in fam.get("values", ())))


def run_replica(rank, addr, port) -> int:
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import Transformer
    from horovod_tpu.serve import ServePolicy
    from horovod_tpu.serve.api import _serve_guard
    from horovod_tpu.serve.replica import run_kv_replica

    model = Transformer(dtype=jnp.float32, **MODEL)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    policy = ServePolicy.from_env()
    guard = _serve_guard(rank) if policy.quarantine else None
    replica = run_kv_replica(model, params, policy, rank=rank,
                             addr=addr, port=port, guard=guard)
    print(f"serve_chaos_worker: rank {rank} drained "
          f"({replica.completed} completed)", flush=True)
    return 0


def run_frontend(world, addr, port) -> int:
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import flight_recorder
    from horovod_tpu.run.rendezvous import KVStoreClient
    from horovod_tpu.serve.queue import KVQueueFrontend, Request

    replicas = world - 1
    client = KVStoreClient(addr, port, scope="serve", timeout=10.0)
    frontend = KVQueueFrontend(client)
    live = frontend.wait_for_replicas(replicas, timeout=60.0)
    print(f"serve_chaos_worker: fleet up: {live}", flush=True)

    rng = np.random.RandomState(0)
    max_new = int(os.environ.get("HOROVOD_SERVE_MAX_NEW_TOKENS", "16"))
    for i in range(N_REQUESTS):
        prompt_len = int(rng.randint(4, 13))
        prompt = rng.randint(1, MODEL["vocab_size"], prompt_len).tolist()
        frontend.submit(Request(uid=f"req-{i}-{uuid.uuid4().hex[:8]}",
                                prompt=prompt, max_new_tokens=max_new))

    completions = []
    deadline = time.monotonic() + DRAIN_TIMEOUT
    while frontend.pending() and time.monotonic() < deadline:
        completions.extend(frontend.poll_responses())
        time.sleep(0.05)
    frontend.stop_fleet()

    done = len(completions)
    zero_lost = done == N_REQUESTS and frontend.pending() == 0
    served_by = sorted({c.rank for c in completions})
    snap = hvd.metrics()
    result = {
        "rank": 0,
        "size": world,
        "generation": 0,
        "submitted": N_REQUESTS,
        "completed": done,
        "zero_lost": zero_lost,
        "requeued": frontend.requeued,
        "dead_ranks": sorted(frontend.dead_ranks),
        "served_by": served_by,
        "net_retries_total": _metric_total(
            snap, "horovod_net_retries_total"),
        "chaos_injected_total": _metric_total(
            snap, "horovod_net_chaos_injected_total"),
    }
    try:  # ship rank 0's dispatch/requeue events into the postmortem
        flight_recorder.dump_debug_state(reason="serve_chaos_complete")
    except Exception:
        pass
    print("CHAOS_RESULT " + json.dumps(result), flush=True)
    return 0 if zero_lost else 3


def main() -> int:
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    world = int(os.environ.get("HOROVOD_SIZE", "4"))
    addr = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_ADDR", "127.0.0.1")
    port = int(os.environ.get("HOROVOD_RENDEZVOUS_HTTP_PORT", "0"))
    if not port:
        print("serve_chaos_worker: no rendezvous port", file=sys.stderr)
        return 2
    if rank == 0:
        return run_frontend(world, addr, port)
    return run_replica(rank, addr, port)


if __name__ == "__main__":
    sys.exit(main())
