"""Worker for the ZeRO-2 chaos cell (``zero2_kill_mid_reducescatter``
in tools/chaos_matrix.py, ISSUE 20).

Stage-2 sharded training: a ``GradReleasePlan(reduce_scatter=True)``
releases each backward bucket as a reduce-scatter (one leaf per
bucket, three per step) and the partition-aligned ``hvd.sharded_adamw``
consumes the resulting ``zero.ShardedGrads`` directly — the full
gradient buffer is never reassembled. At ZERO2_KILL_STEP the kill rank
dies *inside* its second bucket's reduce-scatter release, with bucket
0's reduce-scatter already in flight. The survivors' gather fails the
orphaned stage-2 tokens with WorkersDownError, ``@elastic.run``
re-forms them, and ``zero.resync`` rebuilds the AdamW master/moment
shards under the new world.

Emits ``CHAOS_RESULT {json}`` with the boolean fields the matrix
asserts via ``require_true``: ``resharded`` (the optimizer spec
describes the post-reform world) and ``leases_ok`` (zero outstanding
fusion-buffer leases — every failed token returned its slab).

Invariant: the loss is a plain sum so every averaged gradient element
is exactly 1; sharded AdamW with b1=b2=eps=weight_decay=0 and lr=-1
adds exactly 1.0 per element per step regardless of world size, so
``w == step`` at every commit, across the re-form.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic, flight_recorder
from horovod_tpu.parallel import buckets as buckets_mod

TOTAL_STEPS = int(os.environ.get("CHAOS_TOTAL_STEPS", "8"))
STEP_SLEEP = float(os.environ.get("CHAOS_STEP_SLEEP", "0"))
KILL_STEP = int(os.environ.get("ZERO2_KILL_STEP", "3"))
KILL_RANK = int(os.environ.get("ZERO2_KILL_RANK", "1"))
ORIG_RANK = int(os.environ.get("HOROVOD_RANK", "0"))

PLAN = buckets_mod.GradReleasePlan(bucket_bytes=256,
                                   reduce_scatter=True)

_die_mid_rs = False
_real_release = buckets_mod.GradReleasePlan._release_reduce_scatter


def _release_and_maybe_die(self, bucket, values):
    _real_release(self, bucket, values)
    if _die_mid_rs and bucket.index >= 1:
        # bucket 0's reduce-scatter is already on the wire and later
        # buckets are still differentiating: abrupt death with stage-2
        # tokens genuinely in flight
        os._exit(17)


buckets_mod.GradReleasePlan._release_reduce_scatter = _release_and_maybe_die

OPT = None


def _params():
    # 384 B per leaf > bucket_bytes: one leaf per bucket, three
    # reduce-scatters on the wire per step
    return {"a": jnp.zeros((96,), jnp.float32),
            "b": jnp.zeros((96,), jnp.float32),
            "c": jnp.zeros((96,), jnp.float32)}


def sharded_grads(params):
    def loss(p):
        return sum(x.sum() for x in
                   jax.tree_util.tree_leaves(PLAN.tag(p)))

    return PLAN.gather(jax.grad(loss)(params))


@elastic.run
def train(state):
    global _die_mid_rs
    while state.step < TOTAL_STEPS:
        _die_mid_rs = (ORIG_RANK == KILL_RANK
                       and state.step == KILL_STEP
                       and elastic.restarts() == 0)
        params = {k: jnp.asarray(v) for k, v in state.params.items()}
        sg = sharded_grads(params)
        _die_mid_rs = False
        params, state.optimizer = OPT.apply(params, state.optimizer, sg)
        state.params = {k: np.asarray(v) for k, v in params.items()}
        state.step += 1
        state.commit()
        if STEP_SLEEP:
            time.sleep(STEP_SLEEP)
    return state


def _metric_total(snap, name):
    fam = snap.get(name, {})
    return float(sum(row.get("value", 0.0)
                     for row in fam.get("values", ())))


def main() -> int:
    global OPT

    hvd.init()
    params = _params()
    # b1=b2=eps=weight_decay=0, lr=-1: the AdamW inner reduces to
    # -lr * sign(g) — grads of ones add exactly 1.0 per element per step
    OPT = hvd.sharded_adamw(-1.0, 0.0, 0.0, 0.0, 0.0,
                            partition=PLAN.zero_partition(params))
    state = elastic.ArrayState(
        params={k: np.asarray(v) for k, v in params.items()},
        optimizer=OPT.init(params), step=0)
    train(state)

    from horovod_tpu.runtime.runtime import get_runtime

    mgr = get_runtime().executor.fusion_buffers
    with mgr._lock:
        free = sum(a.nbytes for lst in mgr._free.values() for a in lst)
    leaked = mgr.allocated_bytes() - free
    spec = state.optimizer.spec
    w_arr = np.concatenate([np.asarray(state.params[k]).reshape(-1)
                            for k in sorted(state.params)])
    lockstep = bool(np.all(np.abs(w_arr - TOTAL_STEPS) < 1e-5))

    snap = hvd.metrics()
    result = {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "step": state.step,
        "w": float(w_arr[0]),
        "generation": elastic.restarts(),
        "resharded": (spec.world == hvd.size()
                      and spec.rank == hvd.rank()),
        "leases_ok": leaked == 0,
        "leases_leaked_bytes": int(leaked),
        "wire_released": PLAN.wire_stats()["released"],
        "net_retries_total": _metric_total(
            snap, "horovod_net_retries_total"),
        "net_gave_up_total": _metric_total(
            snap, "horovod_net_gave_up_total"),
        "chaos_injected_total": _metric_total(
            snap, "horovod_net_chaos_injected_total"),
    }
    try:  # the postmortem needs post-reform events
        flight_recorder.dump_debug_state(reason="chaos_run_complete")
    except Exception:
        pass
    print("CHAOS_RESULT " + json.dumps(result), flush=True)
    ok = (state.step == TOTAL_STEPS and lockstep
          and result["resharded"] and result["leases_ok"])
    hvd.shutdown()
    return 0 if ok else 3


if __name__ == "__main__":
    sys.exit(main())
